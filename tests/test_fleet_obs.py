"""Fleet observability plane (ISSUE 16): end-to-end request tracing,
cross-host metric federation, SLO burn-rate gates, and the crash flight
recorder.

The acceptance pins:

- **Tracing**: an ingress request is traced end-to-end — the response
  carries its ``trace_id``, and admission -> queue -> dispatch ->
  respond spans (with the coalesced batch's fan-in links) share one
  trace. A malformed ``traceparent`` mints instead of failing.
- **Federation**: a two-host scrape yields a fleet p99 that matches the
  by-hand merged-bucket computation; counters sum, gauges keep per-host
  identity under a ``host`` label.
- **SLO gates**: a deadline storm flips the multi-window burn-rate gate
  to failing, and a clean drain flips it back through the fast window
  while the slow window still remembers the storm; the
  ``dl4j_slo_burn_rate`` gauge reflects both windows.
- **Flight recorder**: always-on bounded ring; a crash (fit unwind,
  dispatch timeout, dead peer) dumps a debug bundle; a process killed
  mid-dispatch leaves a Perfetto-loadable truncated trace stream AND a
  bundle (``pytest -m chaos``).
- **Multi-host**: two OS worker processes plus an ingress request under
  one ``traceparent`` produce spans from several pids that merge into
  one Perfetto-loadable trace (``pytest -m multihost``).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu import profiler
from deeplearning4j_tpu.faults import ServingLoad
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.profiler import (FlightRecorder, HistogramSnapshot,
                                         MetricsAggregator, SLOEngine,
                                         SLOGate, SLOSpec, TraceContext,
                                         merge_chrome_traces,
                                         parse_exposition, record_span,
                                         run_span, spans_for_trace)
from deeplearning4j_tpu.profiler import tracecontext
from deeplearning4j_tpu.profiler.metrics import MetricsRegistry
from deeplearning4j_tpu.serving import (DeadlineExceededError, HttpIngress,
                                        ModelRegistry, ModelServer,
                                        ServerOverloadedError,
                                        ServingRequest)
from deeplearning4j_tpu.train import updaters

NIN, NOUT = 4, 3
REPO = Path(__file__).resolve().parents[1]


def mlp(seed=42):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(updaters.Sgd(0.1)).list()
            .layer(DenseLayer(nOut=8, activation="relu"))
            .layer(OutputLayer(nOut=NOUT, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(NIN))
            .build())
    return MultiLayerNetwork(conf).init()


def feats(rows, seed=0):
    return np.random.RandomState(seed).randn(rows, NIN).astype(np.float32)


@pytest.fixture()
def net():
    return mlp()


@pytest.fixture()
def traced():
    """Tracing on with a clean ring; everything restored afterwards so
    other tests see the default ship state."""
    tracer = profiler.get_tracer()
    tracer.clear()
    profiler.enable_tracing()
    try:
        yield tracer
    finally:
        profiler.disable_tracing()
        tracer.clear()


def post_json(url, path, payload, headers=None, timeout=30.0):
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    req = urllib.request.Request(f"{url}{path}",
                                 data=json.dumps(payload).encode(),
                                 headers=h)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def get(url, path, headers=None, timeout=10.0):
    req = urllib.request.Request(f"{url}{path}", headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


# ========================================================== trace context
@pytest.mark.quick
class TestTraceContext:
    def test_traceparent_roundtrip(self):
        ctx = TraceContext.new()
        assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
        parsed = TraceContext.from_traceparent(ctx.to_traceparent())
        assert parsed.trace_id == ctx.trace_id
        assert parsed.span_id == ctx.span_id

    def test_malformed_traceparent_mints_none(self):
        bad = [None, "", "garbage", "00-zz-11-01",
               f"ff-{'a' * 32}-{'b' * 16}-01",        # forbidden version
               f"00-{'0' * 32}-{'b' * 16}-01",        # all-zero trace id
               f"00-{'a' * 32}-{'0' * 16}-01"]        # all-zero span id
        for header in bad:
            assert TraceContext.from_traceparent(header) is None, header

    def test_child_keeps_trace_new_span(self):
        root = TraceContext.new()
        kid = root.child()
        assert kid.trace_id == root.trace_id
        assert kid.span_id != root.span_id
        assert kid.parent_id == root.span_id

    def test_record_span_gated(self, traced):
        ctx = TraceContext.new()
        record_span("x", None, 0.0, 1.0)              # ctx None: no-op
        profiler.disable_tracing()
        record_span("x", ctx, 0.0, 1.0)               # tracing off: no-op
        assert spans_for_trace(ctx.trace_id) == []
        profiler.enable_tracing()
        record_span("x", ctx, 0.0, 1.0, args={"k": "v"})
        spans = spans_for_trace(ctx.trace_id)
        assert [s["name"] for s in spans] == ["x"]
        assert spans[0]["args"]["span_id"] == ctx.span_id
        assert spans[0]["args"]["k"] == "v"

    def test_span_nests_under_ambient_and_records_errors(self, traced):
        root = TraceContext.new()
        with tracecontext.use(root):
            with tracecontext.span("hop") as hop:
                assert hop.trace_id == root.trace_id
                assert hop.parent_id == root.span_id
            with pytest.raises(ValueError):
                with tracecontext.span("boom"):
                    raise ValueError("x")
        names = {s["name"]: s for s in spans_for_trace(root.trace_id)}
        assert set(names) == {"hop", "boom"}
        assert names["boom"]["args"]["error"] == "ValueError"

    def test_run_span_stamps_ambient_spans(self, traced):
        with run_span("train:run", model="T") as ctx:
            with profiler.trace_span("train:step"):
                pass
        spans = spans_for_trace(ctx.trace_id)
        names = [s["name"] for s in spans]
        assert "train:run" in names and "train:step" in names
        root = next(s for s in spans if s["name"] == "train:run")
        assert root["args"]["run_id"] == ctx.trace_id

    def test_merge_chrome_traces_dedups_metadata(self):
        meta = {"name": "thread_name", "ph": "M", "pid": 1, "tid": 2,
                "args": {"name": "w"}}
        ev = {"name": "x", "ph": "X", "pid": 1, "tid": 2, "ts": 0, "dur": 1}
        merged = merge_chrome_traces([
            {"traceEvents": [meta, ev]}, [dict(meta), dict(ev)]])
        metas = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
        assert len(metas) == 1
        assert len(merged["traceEvents"]) == 3
        json.dumps(merged)    # Perfetto-loadable = valid JSON document


# ===================================================== serving trace e2e
class TestServingTraceE2E:
    def test_ingress_request_traced_end_to_end(self, net, traced):
        incoming = TraceContext("ab" * 16, "cd" * 8)
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            with HttpIngress(reg, port=0) as ing:
                code, payload, headers = post_json(
                    ing.url, "/v1/models/m:predict",
                    {"instances": feats(2).tolist()},
                    headers={"traceparent": incoming.to_traceparent()})
        assert code == 200
        # THE e2e pin: the response names the trace it belongs to
        assert payload["trace_id"] == incoming.trace_id
        assert headers["traceparent"].split("-")[1] == incoming.trace_id
        spans = spans_for_trace(incoming.trace_id)
        names = {s["name"] for s in spans}
        assert {"ingress:request", "serve:route", "serve:admission",
                "serve:queue", "serve:coalesce", "serve:dispatch",
                "serve:terminal", "ingress:respond"} <= names
        dispatch = next(s for s in spans if s["name"] == "serve:dispatch")
        # fan-in: the dispatch span links the request(s) it served
        links = dispatch["args"]["links"]
        assert any(l["trace_id"] == incoming.trace_id for l in links)
        terminal = next(s for s in spans if s["name"] == "serve:terminal")
        assert terminal["args"]["outcome"] == "completed"

    def test_response_has_trace_id_with_tracing_off(self, net):
        assert not profiler.tracing_enabled()
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            with HttpIngress(reg, port=0) as ing:
                code, payload, headers = post_json(
                    ing.url, "/v1/models/m:predict",
                    {"instances": feats(1).tolist()})
        assert code == 200
        # IDs are always minted; recording stays off
        assert len(payload["trace_id"]) == 32
        assert "traceparent" in headers
        assert spans_for_trace(payload["trace_id"]) == []

    def test_coalesced_fanin_links_every_request(self, net, traced):
        sv = ModelServer(net, batch_limit=8, coalesce_ms=60.0)
        try:
            sv.warmup([(NIN,)])
            reqs = []

            def submit(seed):
                reqs.append(sv.submit(feats(1, seed=seed)))

            ts = [threading.Thread(target=submit, args=(i,))
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for r in reqs:
                r.get(30.0)
        finally:
            sv.close()
        # one coalesced dispatch span, linking BOTH request roots
        dispatches = [s for s in profiler.get_tracer().events()
                      if s["name"] == "serve:dispatch"]
        fan_in = [s for s in dispatches
                  if s["args"].get("requests") == 2]
        assert fan_in, [s["args"] for s in dispatches]
        link_traces = {l["trace_id"] for l in fan_in[-1]["args"]["links"]}
        assert link_traces == {r.trace.trace_id for r in reqs}
        # each request keeps its own trace with its own terminal span
        for r in reqs:
            names = {s["name"] for s in spans_for_trace(r.trace.trace_id)}
            assert "serve:terminal" in names


# ==================================================== metric federation
@pytest.mark.quick
class TestMetricsAggregator:
    HOST_A = """\
# HELP dl4j_serving_latency_seconds Request latency
# TYPE dl4j_serving_latency_seconds histogram
dl4j_serving_latency_seconds_bucket{le="0.1"} 5
dl4j_serving_latency_seconds_bucket{le="0.5"} 8
dl4j_serving_latency_seconds_bucket{le="+Inf"} 10
dl4j_serving_latency_seconds_sum 2.0
dl4j_serving_latency_seconds_count 10
# TYPE dl4j_serving_requests_total counter
dl4j_serving_requests_total{outcome="completed"} 10
# TYPE dl4j_serving_queue_depth gauge
dl4j_serving_queue_depth 3
"""
    HOST_B = """\
# TYPE dl4j_serving_latency_seconds histogram
dl4j_serving_latency_seconds_bucket{le="0.1"} 1
dl4j_serving_latency_seconds_bucket{le="0.5"} 5
dl4j_serving_latency_seconds_bucket{le="+Inf"} 9
dl4j_serving_latency_seconds_sum 3.0
dl4j_serving_latency_seconds_count 9
# TYPE dl4j_serving_requests_total counter
dl4j_serving_requests_total{outcome="completed"} 7
# TYPE dl4j_serving_queue_depth gauge
dl4j_serving_queue_depth 1
"""

    def _agg(self, clock=None):
        agg = MetricsAggregator(max_age=30.0,
                                clock=clock or time.monotonic)
        agg.ingest("a", self.HOST_A)
        agg.ingest("b", self.HOST_B)
        return agg

    def test_fleet_histogram_matches_by_hand_merge(self):
        agg = self._agg()
        snap = agg.fleet_histogram("dl4j_serving_latency_seconds")
        # by hand: cumulative counts sum per bound across hosts
        assert snap.bounds == [0.1, 0.5]
        assert snap.cumulative == [5 + 1, 8 + 5]
        assert snap.count == 19 and snap.sum == 5.0
        # fleet p50 by hand: rank = 0.5*19 = 9.5 falls in (0.1, 0.5]
        # with 6 below and 7 in-bucket -> 0.1 + 0.4 * (9.5-6)/7
        rank, below, in_bucket = 0.5 * 19, 6, 7
        expect_p50 = 0.1 + (0.5 - 0.1) * (rank - below) / in_bucket
        assert abs(agg.quantile("dl4j_serving_latency_seconds", 0.5)
                   - expect_p50) < 1e-12
        # p99 rank (18.81) lands in +Inf: clamps to the top finite bound
        assert agg.quantile("dl4j_serving_latency_seconds", 0.99) == 0.5
        # and the merged quantile math is the same code a local
        # histogram uses (single-host sanity)
        one = HistogramSnapshot([0.1, 0.5], [5, 8], 10, 2.0)
        assert one.quantile(0.5) == 0.1 + 0.4 * (5 - 5) / 3

    def test_counters_sum_and_gauges_keep_host_label(self):
        agg = self._agg()
        assert agg.counter_total("dl4j_serving_requests_total",
                                 {"outcome": "completed"}) == 17.0
        text = agg.exposition()
        assert 'dl4j_serving_requests_total{outcome="completed"} 17' in text
        assert 'dl4j_serving_queue_depth{host="a"} 3' in text
        assert 'dl4j_serving_queue_depth{host="b"} 1' in text
        assert "dl4j_fleet_members 2" in text
        assert "dl4j_fleet_scrapes_total 2" in text
        # merged histogram renders re-cumulated buckets
        assert ('dl4j_serving_latency_seconds_bucket{le="0.5"} 13'
                in text)

    def test_stale_host_drops_out_of_the_merge(self):
        now = [0.0]
        agg = self._agg(clock=lambda: now[0])
        assert agg.hosts() == ["a", "b"]
        now[0] = 20.0
        agg.ingest("b", self.HOST_B)   # b refreshes, a goes stale at 31
        now[0] = 31.0
        assert agg.hosts() == ["b"]
        assert agg.counter_total("dl4j_serving_requests_total",
                                 {"outcome": "completed"}) == 7.0

    def test_fleet_load_totals(self):
        agg = self._agg()
        agg.ingest_load("a", {"totals": {"queue_depth": 3, "max_queue": 8,
                                         "breakers_open": 0,
                                         "shed_rate": 0.2, "ready": True}})
        agg.ingest_load("b", {"totals": {"queue_depth": 1, "max_queue": 8,
                                         "breakers_open": 1,
                                         "shed_rate": 0.0, "ready": True}})
        load = agg.fleet_load()
        assert load["totals"]["queue_depth"] == 4
        assert load["totals"]["max_queue"] == 16
        assert load["totals"]["breakers_open"] == 1
        assert load["totals"]["shed_rate"] == pytest.approx(0.1)
        assert load["totals"]["ready"] is True
        assert load["totals"]["hosts"] == 2

    def test_parse_exposition_tolerates_exemplars(self):
        text = ('# TYPE h histogram\n'
                'h_bucket{le="1.0"} 4 # {trace_id="abc"} 0.73\n'
                'h_bucket{le="+Inf"} 5\n'
                'h_sum 2.5\nh_count 5\n')
        fam = parse_exposition(text)["h"]
        assert fam.samples[("_bucket", (("le", "1.0"),))] == 4.0
        assert fam.samples[("_count", ())] == 5.0


# ======================================================== SLO burn gates
@pytest.mark.quick
class TestSLOGates:
    def _engine(self):
        reg = MetricsRegistry()
        lat = reg.histogram("dl4j_serving_latency_seconds", "lat",
                            buckets=(0.1, 0.25, 1.0))
        outcomes = reg.counter("dl4j_serving_requests_total", "req",
                               labelnames=("outcome",))
        clock = [0.0]
        spec = SLOSpec("serve", objective=0.9, latency_bound=0.25,
                       shed_rate=0.2, availability=0.99,
                       windows=(60.0, 600.0))
        engine = SLOEngine([spec], registry=reg,
                           clock=lambda: clock[0])
        return reg, lat, outcomes, clock, engine

    def test_deadline_storm_flips_gate_then_drain_recovers(self):
        reg, lat, outcomes, clock, engine = self._engine()
        gate = SLOGate(engine)
        # t=0: clean baseline sample
        for _ in range(20):
            lat.observe(0.05)
            outcomes.labels(outcome="completed").inc()
        assert bool(gate())
        # t=30: the storm — slow requests + deadline sheds
        clock[0] = 30.0
        for _ in range(20):
            lat.observe(0.9)
            outcomes.labels(outcome="shed_deadline").inc()
        verdict = gate()
        assert not verdict
        assert verdict.failures == ["serve"]
        windows = verdict.detail["specs"]["serve"]["windows"]
        # the baseline evaluate snapshotted the clean traffic, so the
        # storm delta is 100% bad: latency burn 1.0/0.1 = 10, shed
        # burn 1.0/0.2 = 5
        assert windows["fast"]["burn"] > 1.0
        assert windows["slow"]["burn"] > 1.0
        assert windows["fast"]["criteria"]["latency"] == pytest.approx(10.0)
        assert windows["fast"]["criteria"]["shed"] == pytest.approx(5.0)
        # ...and the gauge carries both windows
        burn = reg.get("dl4j_slo_burn_rate")
        children = {lvals: child.value
                    for lvals, child in burn.children().items()}
        assert children[("serve", "fast")] > 1.0
        assert children[("serve", "slow")] > 1.0
        # t=100: drained — only clean traffic since the storm sample.
        # The fast window (references t=30) sees zero bad observations;
        # the slow window still remembers the storm. Multi-window rule:
        # failing requires BOTH, so the gate flips back immediately.
        clock[0] = 100.0
        for _ in range(20):
            lat.observe(0.05)
            outcomes.labels(outcome="completed").inc()
        verdict = gate()
        assert bool(verdict)
        windows = verdict.detail["specs"]["serve"]["windows"]
        assert windows["fast"]["burn"] <= 1.0
        assert windows["slow"]["burn"] > 1.0
        children = {lvals: child.value
                    for lvals, child in burn.children().items()}
        assert children[("serve", "fast")] <= 1.0
        assert children[("serve", "slow")] > 1.0

    def test_step_time_regression_burn(self):
        reg = MetricsRegistry()
        step = reg.histogram("dl4j_train_iteration_seconds", "step",
                             buckets=(0.1, 1.0))
        clock = [0.0]
        engine = SLOEngine(
            [SLOSpec("train", step_time_baseline=0.1,
                     step_time_regression=1.2)],
            registry=reg, clock=lambda: clock[0])
        step.observe(0.1)
        engine.evaluate()
        clock[0] = 30.0
        for _ in range(10):
            step.observe(0.3)          # 2.5x the allowed 0.12 mean
        detail = engine.evaluate()
        assert detail["failing"] == ["train"]
        crit = detail["specs"]["train"]["windows"]["fast"]["criteria"]
        assert crit["step_time"] == pytest.approx(0.3 / 0.12)

    def test_spec_validation(self):
        with pytest.raises(ValueError):
            SLOSpec("x", objective=1.5)
        with pytest.raises(ValueError):
            SLOSpec("x", shed_rate=0.0)
        with pytest.raises(ValueError):
            SLOSpec("x", availability=1.0)
        with pytest.raises(ValueError):
            SLOSpec("x", windows=(600.0, 60.0))

    def test_verdict_repr_and_bool(self):
        ok = SLOGate(SLOEngine([SLOSpec("s", latency_bound=1.0)],
                               registry=MetricsRegistry()))()
        assert bool(ok) and "passing" in repr(ok)


# ============================================================= exemplars
@pytest.mark.quick
class TestExemplars:
    def test_exemplar_rendered_only_in_openmetrics(self):
        reg = MetricsRegistry()
        h = reg.histogram("dl4j_x_seconds", "x", buckets=(0.1, 1.0))
        h.observe(0.05, exemplar="ab" * 16)
        h.observe(0.5)                   # no exemplar on this bucket
        text = reg.exposition()
        assert "trace_id" not in text    # 0.0.4 dialect: no exemplars
        assert not text.rstrip().endswith("# EOF")
        om = reg.exposition(openmetrics=True)
        assert ('dl4j_x_seconds_bucket{le="0.1"} 1 '
                '# {trace_id="' + "ab" * 16 + '"} 0.05') in om
        assert om.rstrip().endswith("# EOF")

    def test_latest_exemplar_wins_per_bucket(self):
        reg = MetricsRegistry()
        h = reg.histogram("dl4j_y_seconds", "y", buckets=(1.0,))
        h.observe(0.1, exemplar="first")
        h.observe(0.2, exemplar="second")
        om = reg.exposition(openmetrics=True)
        assert 'trace_id="second"' in om and 'trace_id="first"' not in om

    def test_serving_latency_carries_trace_exemplar(self, net, traced):
        sv = ModelServer(net, batch_limit=4, coalesce_ms=0.0,
                         name="exemplar-test")
        try:
            sv.warmup([(NIN,)])
            req = sv.submit(feats(1))
            req.get(30.0)
        finally:
            sv.close()
        om = profiler.get_registry().exposition(openmetrics=True)
        assert f'trace_id="{req.trace.trace_id}"' in om


# ======================================================= flight recorder
@pytest.mark.quick
class TestFlightRecorder:
    def test_ring_is_bounded_and_always_on(self):
        rec = FlightRecorder(capacity=8)
        assert not profiler.tracing_enabled()   # no gate: always on
        for i in range(20):
            rec.record("k", i=i)
        evs = rec.events()
        assert len(evs) == 8
        assert [e["i"] for e in evs] == list(range(12, 20))
        assert rec.events(last=2)[-1]["i"] == 19

    def test_dump_bundle_contents_and_rate_limit(self, tmp_path):
        rec = FlightRecorder(capacity=16, directory=str(tmp_path),
                             min_dump_interval=60.0)
        rec.record("serving:dispatch", server="s", rows=2)
        path = rec.dump("dispatch_timeout",
                        exc=TimeoutError("replica hung"))
        assert path is not None
        bundle = Path(path)
        for name in ("events.json", "trace.json", "metrics.txt",
                     "config.json", "reason.txt"):
            assert (bundle / name).exists(), name
        events = json.loads((bundle / "events.json").read_text())
        assert any(e["kind"] == "serving:dispatch" for e in events)
        reason = (bundle / "reason.txt").read_text()
        assert "dispatch_timeout" in reason and "replica hung" in reason
        config = json.loads((bundle / "config.json").read_text())
        assert config["pid"] == os.getpid()
        # per-reason rate limit: an immediate repeat is suppressed...
        assert rec.dump("dispatch_timeout") is None
        # ...but a different reason still dumps
        assert rec.dump("dead_peer") is not None

    def test_dump_never_raises(self):
        rec = FlightRecorder(capacity=4, min_dump_interval=0.0)
        rec.record("x")
        # an unwritable directory must degrade, not throw — the flight
        # recorder runs on crash paths
        assert rec.dump("r", directory="/dev/null/nope") is None

    def test_fit_crash_dumps_a_bundle(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.profiler import flightrec
        from deeplearning4j_tpu.train.resilience import fit_scope
        monkeypatch.setenv("DL4J_FLIGHTREC_DIR", str(tmp_path))
        rec = flightrec.get_flight_recorder()
        rec._last_dump = {}              # reset rate-limit for the test

        class Model:
            _epoch = 0

        with pytest.raises(RuntimeError, match="boom"):
            with fit_scope(None, Model(), epochs=1):
                raise RuntimeError("boom")
        bundles = list(tmp_path.glob("flightrec-*"))
        assert bundles, "fit crash left no flight-recorder bundle"
        reason = (bundles[0] / "reason.txt").read_text()
        assert "fit:RuntimeError" in reason and "boom" in reason


# ================================================================= chaos
@pytest.mark.chaos
class TestChaosTraces:
    def test_every_terminal_outcome_carries_a_trace(self, net, traced):
        """Deadline-storm replay: every request — completed, shed at
        admission, or deadline-expired — ends with a terminal span on
        its own trace (admission rejections expose ``trace_id`` on the
        raised error)."""
        sv = ModelServer(net, batch_limit=2, max_queue=2, coalesce_ms=0.5,
                         default_deadline=0.05)
        try:
            sv.warmup([(NIN,)])
            load = ServingLoad.seeded(5, mix="burst", n=40, rps=400.0,
                                      n_bursts=2, burst_size=15,
                                      max_rows=1)
            results = load.replay(sv.submit, (NIN,))
            outcomes = {"completed": 0, "shed": 0, "deadline": 0}
            for _, h in results:
                if isinstance(h, ServerOverloadedError):
                    outcomes["shed"] += 1
                    # the admission rejection names its trace...
                    tid = h.trace_id
                    assert len(tid) == 32
                else:
                    assert isinstance(h, ServingRequest)
                    tid = h.trace.trace_id
                    try:
                        h.get(30.0)
                        outcomes["completed"] += 1
                    except DeadlineExceededError:
                        outcomes["deadline"] += 1
                # ...and every outcome recorded a terminal span on it
                terminals = [s for s in spans_for_trace(tid)
                             if s["name"] == "serve:terminal"]
                assert len(terminals) == 1, (tid, terminals)
            assert sum(outcomes.values()) == 40
            assert outcomes["completed"] > 0
            # the storm actually exercised non-completed terminals
            assert outcomes["shed"] + outcomes["deadline"] > 0
            # outcome args match: completed terminals say so
            completed = [
                s for _, h in results if isinstance(h, ServingRequest)
                and h._error is None
                for s in spans_for_trace(h.trace.trace_id)
                if s["name"] == "serve:terminal"]
            assert all(s["args"]["outcome"] == "completed"
                       for s in completed)
        finally:
            sv.close()

    def test_killed_mid_dispatch_leaves_trace_and_bundle(self, tmp_path):
        """A process killed while a dispatch is in flight leaves (a) a
        Perfetto-loadable truncated trace stream and (b) a flight
        recorder bundle from the dispatch-timeout watchdog that fired
        before the kill — the crash-forensics contract."""
        script = tmp_path / "victim.py"
        stream = tmp_path / "stream.trace.json"
        frdir = tmp_path / "flightrec"
        frdir.mkdir()
        script.write_text(_KILL_WORKER)
        env = dict(os.environ, DL4J_REPO=str(REPO), JAX_PLATFORMS="cpu",
                   TRACE_STREAM=str(stream), FLIGHTREC_DIR=str(frdir))
        proc = subprocess.run([sys.executable, str(script)],
                              capture_output=True, text=True, env=env,
                              timeout=120)
        assert proc.returncode == 9, proc.stdout + proc.stderr
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("RESULT ")][-1]
        info = json.loads(line[len("RESULT "):])
        # (a) the streamed trace survives truncated and loads
        raw = stream.read_text()
        assert raw.startswith("[")
        assert not raw.rstrip().endswith("]")    # killed = never finalized
        events = json.loads(raw.rstrip().rstrip(",") + "]")
        ok_spans = [e for e in events
                    if e.get("args", {}).get("trace_id") == info["ok_trace"]]
        assert {"serve:dispatch", "serve:terminal"} <= \
            {e["name"] for e in ok_spans}
        # the hung request got at least as far as admission on disk
        hung = [e for e in events
                if e.get("args", {}).get("trace_id") == info["hung_trace"]]
        assert any(e["name"] == "serve:admission" for e in hung)
        # (b) the watchdog's bundle is on disk
        bundles = list(frdir.glob("flightrec-*dispatch_timeout*"))
        assert bundles, list(frdir.iterdir())
        evs = json.loads((bundles[0] / "events.json").read_text())
        assert any(e["kind"] == "serving:dispatch_failure" for e in evs)


_KILL_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["DL4J_REPO"])
import numpy as np
from deeplearning4j_tpu import profiler
from deeplearning4j_tpu.profiler import flightrec
from deeplearning4j_tpu.serving import ModelServer

profiler.enable_tracing()
profiler.get_tracer().stream_to(os.environ["TRACE_STREAM"],
                                flush_every=1)
flightrec.configure(directory=os.environ["FLIGHTREC_DIR"],
                    min_dump_interval=0.0)

def fwd(x):
    if float(np.asarray(x).ravel()[0]) < 0:
        time.sleep(60.0)                    # the hung replica
    return np.zeros((int(np.asarray(x).shape[0]), 3), np.float32)

sv = ModelServer(None, forward=fwd, batch_limit=2, max_queue=8,
                 coalesce_ms=0.0, max_retries=0, replica_timeout=0.3,
                 name="victim")
sv.warmup([(4,)])
ok = sv.submit(np.ones((1, 4), np.float32))
ok.get(30.0)
hung = sv.submit(np.full((1, 4), -1.0, np.float32))
deadline = time.monotonic() + 30.0
# wait for the watchdog to abandon the dispatch and dump, then die
# with the forward thread still stuck in fwd() — mid-dispatch
while time.monotonic() < deadline:
    if any(p.name.startswith("flightrec-")
           for p in os.scandir(os.environ["FLIGHTREC_DIR"])):
        break
    time.sleep(0.05)
print("RESULT " + json.dumps({"ok_trace": ok.trace.trace_id,
                              "hung_trace": hung.trace.trace_id}))
sys.stdout.flush()
os._exit(9)
"""


# ============================================================= multihost
@pytest.mark.multihost
class TestMultihostTrace:
    def test_barrier_and_ingress_share_one_trace(self, net, traced,
                                                 tmp_path):
        """THE multihost pin: two OS worker processes run a barrier
        round and the parent serves an ingress request, all under ONE
        traceparent — the merged Chrome trace stitches spans from >= 3
        pids into a single Perfetto-loadable flow."""
        from deeplearning4j_tpu.distributed import SocketCoordinatorServer

        root = TraceContext.new()
        worker = tmp_path / "worker.py"
        worker.write_text(_TRACE_WORKER)
        docs = []
        with SocketCoordinatorServer(participants=2) as srv:
            procs = []
            for rank in ("0", "1"):
                env = dict(os.environ, DL4J_REPO=str(REPO),
                           COORD_RANK=rank, COORD_ADDR=srv.address,
                           TRACEPARENT=root.to_traceparent())
                procs.append(subprocess.Popen(
                    [sys.executable, str(worker)],
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    env=env, text=True))
            for p in procs:
                out, _ = p.communicate(timeout=60)
                assert p.returncode == 0, out[-2000:]
                line = [l for l in out.splitlines()
                        if l.startswith("RESULT ")][-1]
                docs.append(json.loads(line[len("RESULT "):]))
        # the ingress leg of the same trace, served by the parent
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            with HttpIngress(reg, port=0) as ing:
                code, payload, _ = post_json(
                    ing.url, "/v1/models/m:predict",
                    {"instances": feats(1).tolist()},
                    headers={"traceparent": root.to_traceparent()})
        assert code == 200 and payload["trace_id"] == root.trace_id

        merged = merge_chrome_traces(
            docs + [profiler.get_tracer().to_chrome_trace()])
        spans = spans_for_trace(root.trace_id, merged["traceEvents"])
        names = {s["name"] for s in spans}
        # client barrier spans (workers), server round spans (parent
        # coordinator), and the ingress request — one trace
        assert "coord:barrier" in names
        assert "coord:round" in names
        assert "ingress:request" in names
        pids = {s["pid"] for s in spans}
        assert len(pids) >= 3, pids
        # agreement still holds under tracing
        assert {d["agreed"] for d in docs} == {7}
        json.dumps(merged)      # Perfetto-loadable = valid JSON document


_TRACE_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["DL4J_REPO"])
from deeplearning4j_tpu import profiler
from deeplearning4j_tpu.distributed import SocketCoordinator
from deeplearning4j_tpu.profiler import tracecontext

profiler.enable_tracing()
ctx = tracecontext.TraceContext.from_traceparent(
    os.environ["TRACEPARENT"])
rank = os.environ["COORD_RANK"]
c = SocketCoordinator(os.environ["COORD_ADDR"], participant=f"p{rank}",
                      heartbeat_interval=0.2)
with tracecontext.use(ctx):
    agreed = c.resume_barrier(f"p{rank}", 7 if rank == "0" else 12,
                              timeout=20.0)
c.close()
doc = profiler.get_tracer().to_chrome_trace()
doc["agreed"] = agreed
print("RESULT " + json.dumps(doc))
"""
