"""Static analyzer (ISSUE 3): one seeded misconfiguration per diagnostic
code, clean-bill assertions over the whole model zoo + fixtures, the
recompile-churn detector, strict init, did-you-mean kwarg rejection, the
EarlyStoppingTrainer megastep path, the CLI, and the repo lint gate."""

import ast
import importlib.util
import pathlib
import subprocess
import sys
import warnings

import numpy as np
import pytest

import deeplearning4j_tpu.analysis as analysis
from deeplearning4j_tpu.analysis import (DIAGNOSTIC_CODES, Diagnostic,
                                         MeshSpec, ModelValidationError,
                                         PipelineSpec,
                                         RecompileChurnDetector, Severity,
                                         analyze, get_churn_detector)
from deeplearning4j_tpu.data.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.config import (InputType, MultiLayerConfiguration,
                                          NeuralNetConfiguration)
from deeplearning4j_tpu.nn.graph import (ComputationGraph, ElementWiseVertex,
                                         MergeVertex)
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer, LSTM,
                                          OutputLayer, RnnOutputLayer,
                                          SubsamplingLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.updaters import Adam, Sgd

REPO = pathlib.Path(__file__).resolve().parent.parent


def _builder(updater=None):
    return (NeuralNetConfiguration.Builder()
            .seed(7).updater(updater or Sgd(0.1)).weightInit("xavier"))


def _mlp_conf(n_in=4, hidden=8, n_out=2, updater=None):
    return (_builder(updater).list()
            .layer(DenseLayer(nOut=hidden, activation="relu"))
            .layer(OutputLayer(nOut=n_out, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(n_in))
            .build())


def _graph_builder():
    return (_builder().graphBuilder()
            .addInputs("in")
            .setInputTypes(InputType.feedForward(4)))


def _one_hot(n, k=2, seed=0):
    rng = np.random.RandomState(seed)
    y = np.zeros((n, k), np.float32)
    y[np.arange(n), rng.randint(0, k, n)] = 1.0
    return y


class TestSeededDiagnostics:
    """Each documented code fires on its seeded misconfiguration."""

    def test_e001_nin_mismatch(self):
        conf = (_builder().list()
                .layer(DenseLayer(nIn=300, nOut=16))
                .layer(OutputLayer(nOut=4))
                .setInputType(InputType.feedForward(128))
                .build())
        report = conf.validate()
        assert "DL4J-E001" in report.codes()
        assert not report.ok()

    def test_e001_unresolvable_nin(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=16))
                .layer(OutputLayer(nOut=4, nIn=16))
                .build())     # no setInputType -> nIn can't be inferred
        assert "DL4J-E001" in conf.validate().codes()

    def test_e002_cycle(self):
        g = (_graph_builder()
             .addLayer("a", DenseLayer(nIn=4, nOut=4), "b")
             .addLayer("b", DenseLayer(nIn=4, nOut=4), "a")
             .addLayer("out", OutputLayer(nIn=4, nOut=2), "b")
             .setOutputs("out"))
        report = g.validate()      # build() would raise; validate reports
        assert "DL4J-E002" in report.codes()

    def test_e003_undefined_input(self):
        g = (_graph_builder()
             .addLayer("out", OutputLayer(nIn=4, nOut=2), "nonexistent")
             .setOutputs("out"))
        report = g.validate()
        assert "DL4J-E003" in report.codes()
        assert report.errors()

    def test_e003_dangling_vertex(self):
        g = (_graph_builder()
             .addLayer("used", DenseLayer(nOut=4), "in")
             .addLayer("orphan", DenseLayer(nOut=4), "in")
             .addLayer("out", OutputLayer(nOut=2), "used")
             .setOutputs("out"))
        report = analyze(g.build())
        dangling = [d for d in report if d.code == "DL4J-E003"]
        assert dangling and dangling[0].severity is Severity.WARNING
        assert "orphan" in dangling[0].location

    def test_e004_duplicate_graph_name(self):
        g = (_graph_builder()
             .addLayer("fc", DenseLayer(nOut=4), "in")
             .addLayer("fc", DenseLayer(nOut=4), "in")
             .addLayer("out", OutputLayer(nOut=2), "fc")
             .setOutputs("out"))
        assert "DL4J-E004" in g.validate().codes()

    def test_e004_duplicate_explicit_layer_name(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=8, name="fc"))
                .layer(DenseLayer(nOut=8, name="fc"))
                .layer(OutputLayer(nOut=2))
                .setInputType(InputType.feedForward(4))
                .build())
        assert "DL4J-E004" in conf.validate().codes()

    def test_e005_missing_cnn_to_dense_flatten(self):
        conf = (_builder().list()
                .layer(ConvolutionLayer(nIn=1, nOut=8, kernelSize=(3, 3)))
                .layer(DenseLayer(nIn=800, nOut=10))
                .layer(OutputLayer(nIn=10, nOut=2))
                .build())     # no input type -> no auto preprocessor
        assert "DL4J-E005" in conf.validate().codes()

    def test_e006_elementwise_shape_conflict(self):
        g = (_builder().graphBuilder()
             .addInputs("in")
             .setInputTypes(InputType.convolutional(8, 8, 3))
             .addLayer("a", ConvolutionLayer(nOut=4, kernelSize=(1, 1)), "in")
             .addLayer("b", ConvolutionLayer(nOut=8, kernelSize=(1, 1)), "in")
             .addVertex("add", ElementWiseVertex("Add"), "a", "b")
             .addLayer("out", OutputLayer(nOut=2), "add")
             .setOutputs("out"))
        assert "DL4J-E006" in analyze(g.build()).codes()

    def test_e006_merge_spatial_conflict(self):
        g = (_builder().graphBuilder()
             .addInputs("in")
             .setInputTypes(InputType.convolutional(8, 8, 3))
             .addLayer("a", ConvolutionLayer(nOut=4, kernelSize=(1, 1)), "in")
             .addLayer("b", ConvolutionLayer(nOut=4, kernelSize=(1, 1),
                                             stride=(2, 2)), "in")
             .addVertex("cat", MergeVertex(), "a", "b")
             .addLayer("out", OutputLayer(nOut=2), "cat")
             .setOutputs("out"))
        assert "DL4J-E006" in analyze(g.build()).codes()

    def test_e007_shape_inference_failure(self):
        lb = (_builder().list()
              .layer(DenseLayer())          # nOut missing
              .layer(OutputLayer(nOut=2))
              .setInputType(InputType.feedForward(4)))
        assert "DL4J-E007" in analyze(lb).codes()   # unbuilt builder

    def test_e008_missing_loss_head(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=8))
                .layer(DenseLayer(nOut=2))
                .setInputType(InputType.feedForward(4))
                .build())
        assert "DL4J-E008" in conf.validate().codes()

    def test_w001_softmax_mse(self):
        conf = (_builder().list()
                .layer(OutputLayer(nOut=4, lossFunction="mse",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(4))
                .build())
        report = conf.validate()
        assert "DL4J-W001" in report.codes()
        assert report.ok()                  # warning, not error
        assert not report.ok(warnings_as_errors=True)

    def test_w001_sigmoid_multiclass(self):
        conf = (_builder().list()
                .layer(OutputLayer(nOut=4, lossFunction="mcxent",
                                   activation="sigmoid"))
                .setInputType(InputType.feedForward(4))
                .build())
        assert "DL4J-W001" in conf.validate().codes()

    def test_w002_tbptt_without_recurrence(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=8))
                .layer(OutputLayer(nOut=2))
                .setInputType(InputType.feedForward(4))
                .backpropType("tbptt", 16)
                .build())
        assert "DL4J-W002" in conf.validate().codes()

    def test_w002_absent_on_recurrent_net(self):
        conf = (_builder().list()
                .layer(LSTM(nOut=8))
                .layer(RnnOutputLayer(nOut=2))
                .setInputType(InputType.recurrent(4, 10))
                .backpropType("tbptt", 16)
                .build())
        assert "DL4J-W002" not in conf.validate().codes()

    def test_w003_frozen_with_stateful_updater(self):
        net = MultiLayerNetwork(_mlp_conf(updater=Adam(1e-3)))
        net._frozen_layers = {0}
        report = net.validate()
        assert "DL4J-W003" in report.codes()
        # Sgd is stateless -> no warning
        net2 = MultiLayerNetwork(_mlp_conf(updater=Sgd(0.1)))
        net2._frozen_layers = {0}
        assert "DL4J-W003" not in net2.validate().codes()

    def test_w101_mxu_padding_waste(self):
        conf = _mlp_conf(hidden=300)        # 300 -> 384 lanes, 22% dead
        report = conf.validate()
        w101 = [d for d in report if d.code == "DL4J-W101"]
        assert w101 and "384" in w101[0].message
        assert "DL4J-W101" not in _mlp_conf(hidden=512).validate().codes()

    def test_w102_non_native_dtype(self):
        conf = (_builder().dataType("float64").list()
                .layer(OutputLayer(nOut=2))
                .setInputType(InputType.feedForward(4))
                .build())
        assert "DL4J-W102" in conf.validate().codes()

    def test_w103_batch_mesh_divisibility(self):
        conf = _mlp_conf()
        assert "DL4J-W103" in conf.validate(batch_size=6,
                                            data_devices=4).codes()
        assert "DL4J-W103" not in conf.validate(batch_size=8,
                                                data_devices=4).codes()


class TestChurnDetector:
    def test_w201_fires_past_threshold(self):
        from deeplearning4j_tpu.profiler.metrics import MetricsRegistry
        reg = MetricsRegistry()
        det = RecompileChurnDetector(threshold=3, registry=reg)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            results = [det.record("test.site", (("shape", i),))
                       for i in range(5)]
        assert results[:3] == [None, None, None]
        assert isinstance(results[3], Diagnostic)       # 4th distinct > 3
        assert results[3].code == "DL4J-W201"
        assert results[4] is None                       # flagged once
        assert any("DL4J-W201" in str(w.message) for w in caught)
        # repeats are free
        assert det.record("test.site", (("shape", 0),)) is None
        assert det.signature_count("test.site") == 5
        child = reg.get("dl4j_recompiles_total").children()[("test.site",)]
        assert child.value == 5
        assert [d.code for d in det.diagnostics_for(None)] == ["DL4J-W201"]
        det.reset()
        assert det.signature_count("test.site") == 0

    def test_fingerprint_shape_dtype_sensitivity(self):
        a = np.zeros((4, 3), np.float32)
        b = np.zeros((5, 3), np.float32)
        c = np.zeros((4, 3), np.float64)
        from deeplearning4j_tpu.analysis import array_fingerprint
        assert array_fingerprint(a) != array_fingerprint(b)
        assert array_fingerprint(a) != array_fingerprint(c)
        assert array_fingerprint(a, None) == array_fingerprint(a, None)

    def test_model_fit_churn_surfaces_in_validate(self):
        det = get_churn_detector()
        old_threshold = det.threshold
        det.threshold = 3
        try:
            net = MultiLayerNetwork(_mlp_conf()).init()
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                for n in (1, 2, 3, 4, 5):   # 5 distinct batch shapes
                    net.fit(DataSet(np.random.RandomState(n)
                                    .rand(n, 4).astype(np.float32),
                                    _one_hot(n)))
            report = net.validate()
            assert "DL4J-W201" in report.codes()
            # a fresh model has no churn findings
            fresh = MultiLayerNetwork(_mlp_conf())
            assert "DL4J-W201" not in fresh.validate().codes()
        finally:
            det.threshold = old_threshold


class TestEntryPoints:
    def test_strict_init_raises_on_errors(self):
        conf = (_builder().list()
                .layer(DenseLayer(nIn=300, nOut=16))
                .layer(OutputLayer(nOut=4))
                .setInputType(InputType.feedForward(128))
                .build())
        net = MultiLayerNetwork(conf)
        with pytest.raises(ModelValidationError) as ei:
            net.init(strict=True)
        assert "DL4J-E001" in str(ei.value)
        net.init()                          # non-strict path unchanged
        assert net._initialized

    def test_strict_init_graph(self):
        g = (_graph_builder()
             .addLayer("fc", DenseLayer(nOut=8), "in")
             .addLayer("out", DenseLayer(nOut=2), "fc")   # not a loss head
             .setOutputs("out"))
        net = ComputationGraph(g.build())
        with pytest.raises(ModelValidationError):
            net.init(strict=True)

    def test_strict_init_passes_clean_model(self):
        net = MultiLayerNetwork(_mlp_conf())
        net.init(strict=True)
        assert net._initialized

    def test_validate_runs_no_jax_trace(self):
        # validate() on an uninitialized net must not allocate params
        net = MultiLayerNetwork(_mlp_conf())
        net.validate()
        assert not net._initialized

    def test_tbptt_config_roundtrip(self):
        conf = (_builder().list()
                .layer(LSTM(nOut=8))
                .layer(RnnOutputLayer(nOut=2))
                .setInputType(InputType.recurrent(4, 10))
                .backpropType("tbptt", 16)
                .build())
        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert back.backprop_type == "tbptt"
        assert back.tbptt_length == 16


class TestDidYouMean:
    def test_layer_kwarg_typo(self):
        with pytest.raises(TypeError, match=r"did you mean 'nOut'"):
            DenseLayer(nOutt=8)

    def test_layer_kwarg_unknown(self):
        with pytest.raises(TypeError, match="unknown config key"):
            ConvolutionLayer(nOut=8, zebra=1)

    def test_subclass_kwargs_still_accepted(self):
        layer = ConvolutionLayer(nOut=8, kernelSize=(3, 3),
                                 convolutionMode="same", hasBias=False)
        assert layer.mode == "same" and not layer.has_bias

    def test_builder_method_typo(self):
        with pytest.raises(AttributeError, match="did you mean 'updater'"):
            NeuralNetConfiguration.Builder().updatr(Sgd(0.1))

    def test_list_builder_method_typo(self):
        with pytest.raises(AttributeError, match="setInputType"):
            _builder().list().setInputTyp(InputType.feedForward(4))


class TestZooCleanBill:
    def test_every_zoo_model_is_clean(self):
        from deeplearning4j_tpu.models.zoo import all_zoo_models
        for name, net in all_zoo_models():
            report = analyze(net)
            assert report.ok(warnings_as_errors=True), \
                f"{name} not clean:\n{report.format()}"

    def test_fixture_configs_are_clean(self):
        fixtures = [
            _mlp_conf(),
            (_builder().list()
             .layer(ConvolutionLayer(nOut=8, kernelSize=(3, 3)))
             .layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
             .layer(DenseLayer(nOut=16, activation="relu"))
             .layer(OutputLayer(nOut=2))
             .setInputType(InputType.convolutional(12, 12, 1))
             .build()),
            (_builder().list()
             .layer(LSTM(nOut=8))
             .layer(RnnOutputLayer(nOut=3))
             .setInputType(InputType.recurrent(5, 7))
             .build()),
        ]
        for conf in fixtures:
            report = conf.validate()
            assert report.ok(warnings_as_errors=True), report.format()

    def test_documented_code_table_is_complete(self):
        assert len(DIAGNOSTIC_CODES) >= 10
        for code in DIAGNOSTIC_CODES:
            assert code.startswith("DL4J-")
        with pytest.raises(ValueError):
            Diagnostic("DL4J-E999", Severity.ERROR, "x", "undocumented")


class TestPureStatic:
    """The analyzer is jax-free: no module-scope jax imports (AST check)
    and the package imports with jax blocked (subprocess check)."""

    @staticmethod
    def _module_scope_imports(tree):
        out = []

        def visit(stmts):
            for node in stmts:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue          # lazy imports are fine
                if isinstance(node, ast.Import):
                    out.extend(a.name for a in node.names)
                elif isinstance(node, ast.ImportFrom):
                    out.append(node.module or "")
                for field in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(node, field, None)
                    if sub:
                        visit([s for s in sub if isinstance(s, ast.stmt)])
        visit(tree.body)
        return out

    def test_no_module_scope_jax_imports(self):
        pkg = pathlib.Path(analysis.__file__).parent
        for py in sorted(pkg.glob("*.py")):
            tree = ast.parse(py.read_text(encoding="utf-8"))
            for mod in self._module_scope_imports(tree):
                root = mod.split(".")[0]
                assert root not in ("jax", "jaxlib"), \
                    f"{py.name} imports {mod} at module scope"

    def test_analysis_package_imports_with_jax_blocked(self):
        code = (
            "import sys\n"
            "sys.modules['jax'] = None\n"           # ImportError on import
            "sys.modules['jax.numpy'] = None\n"
            "import deeplearning4j_tpu.analysis as a\n"
            "r = a.ValidationReport(subject='x')\n"
            "a.get_churn_detector().record('s', ((1,), 'f32', False))\n"
            "d = a.Diagnostic('DL4J-E001', a.Severity.ERROR, 'l', 'm')\n"
            "print('PURE-STATIC-OK')\n")
        proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "PURE-STATIC-OK" in proc.stdout


class TestEarlyStoppingMegasteps:
    def _train(self, steps_per_dispatch):
        from deeplearning4j_tpu.train.earlystopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            EarlyStoppingTrainer, MaxEpochsTerminationCondition)
        rng = np.random.RandomState(0)
        train = DataSet(rng.rand(32, 4).astype(np.float32), _one_hot(32))
        val = DataSet(rng.rand(16, 4).astype(np.float32), _one_hot(16, seed=1))
        net = MultiLayerNetwork(_mlp_conf()).init(seed=99)
        cfg = EarlyStoppingConfiguration.Builder() \
            .scoreCalculator(DataSetLossCalculator(
                ListDataSetIterator(val, 8))) \
            .epochTerminationConditions(MaxEpochsTerminationCondition(2)) \
            .build()
        trainer = EarlyStoppingTrainer(
            cfg, net, ListDataSetIterator(train, 8),
            steps_per_dispatch=steps_per_dispatch)
        result = trainer.fit()
        return net, result

    def test_k_step_path_matches_single_step(self):
        net1, res1 = self._train(1)
        net2, res2 = self._train(2)
        assert res1.total_epochs == res2.total_epochs == 2
        assert net1._iteration == net2._iteration == 8   # 4 batches x 2
        np.testing.assert_allclose(np.asarray(net1.params()),
                                   np.asarray(net2.params()),
                                   rtol=0, atol=0)       # bit-exact
        assert res2.best_score == pytest.approx(res1.best_score)

    def test_iteration_condition_checked_between_dispatches(self):
        from deeplearning4j_tpu.train.earlystopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            EarlyStoppingTrainer, MaxEpochsTerminationCondition,
            MaxScoreIterationTerminationCondition)
        rng = np.random.RandomState(0)
        train = DataSet(rng.rand(32, 4).astype(np.float32), _one_hot(32))
        net = MultiLayerNetwork(_mlp_conf()).init(seed=99)
        cfg = EarlyStoppingConfiguration.Builder() \
            .scoreCalculator(DataSetLossCalculator(
                ListDataSetIterator(train, 8))) \
            .epochTerminationConditions(MaxEpochsTerminationCondition(3)) \
            .iterationTerminationConditions(
                MaxScoreIterationTerminationCondition(-1.0)) \
            .build()
        result = EarlyStoppingTrainer(cfg, net,
                                      ListDataSetIterator(train, 8),
                                      steps_per_dispatch=2).fit()
        assert result.termination_reason == "IterationTerminationCondition"
        assert net._iteration == 2      # one 2-step dispatch, then stop


class TestCli:
    def test_zoo_lint_exits_zero(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["--zoo"]) == 0
        out = capsys.readouterr().out
        assert "16 model(s) linted: 16 clean" in out

    def test_single_model_by_name(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["LeNet"]) == 0
        assert "LeNet: clean" in capsys.readouterr().out

    def test_findings_fail_the_exit_code(self, capsys, tmp_path,
                                         monkeypatch):
        mod = tmp_path / "badmodel.py"
        mod.write_text(
            "from deeplearning4j_tpu.nn.config import (InputType,\n"
            "    NeuralNetConfiguration)\n"
            "from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer\n"
            "conf = (NeuralNetConfiguration.Builder().list()\n"
            "        .layer(DenseLayer(nIn=300, nOut=16))\n"
            "        .layer(OutputLayer(nOut=4))\n"
            "        .setInputType(InputType.feedForward(128))\n"
            "        .build())\n")
        monkeypatch.syspath_prepend(str(tmp_path))
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["badmodel:conf"]) == 1
        assert "DL4J-E001" in capsys.readouterr().out


class TestRepoLintGate:
    def test_repo_lints_clean(self, capsys):
        spec = importlib.util.spec_from_file_location(
            "repo_lint", REPO / "tools" / "lint.py")
        lint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lint)
        rc = lint.run_fallback(lint.DEFAULT_PATHS)
        out = capsys.readouterr().out
        assert rc == 0, f"repo lint found issues:\n{out}"


def _wide_mlp(n_in=4096, hidden=4096, n_out=2):
    """64 MiB hidden weight — big enough for the replicated-giant lints."""
    return (_builder().list()
            .layer(DenseLayer(nOut=hidden, activation="relu"))
            .layer(OutputLayer(nOut=n_out))
            .setInputType(InputType.feedForward(n_in))
            .build())


class TestMeshSpec:
    def test_parse_and_coerce(self):
        spec = MeshSpec.parse("data=4,model=2")
        assert spec.axes == {"data": 4, "model": 2}
        assert MeshSpec.coerce("data=8").size("data") == 8
        assert MeshSpec.coerce({"data": 2}).axes == {"data": 2}
        same = MeshSpec({"data": 2})
        assert MeshSpec.coerce(same) is same
        assert MeshSpec.coerce(None) is None

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError):
            MeshSpec.parse("data")
        with pytest.raises(ValueError):
            MeshSpec.parse("data=x")
        with pytest.raises(ValueError):
            MeshSpec.parse("")
        with pytest.raises(TypeError):
            MeshSpec.coerce(42)

    def test_coerce_runtime_device_mesh(self):
        from deeplearning4j_tpu.parallel.mesh import DeviceMesh
        dm = DeviceMesh.create(data=4, model=2)
        spec = MeshSpec.coerce(dm)
        assert spec.axes["data"] == 4 and spec.axes["model"] == 2
        assert dm.spec(hbm_gb=1.0).hbm_gb == 1.0

    def test_pipeline_stage_assignment(self):
        assert PipelineSpec(2).stage_of(4) == [0, 0, 1, 1]
        assert PipelineSpec(2, boundaries=[0, 3]).stage_of(4) == [0, 0, 0, 1]
        with pytest.raises(ValueError):
            PipelineSpec(2, boundaries=[1, 3]).stage_of(4)  # must start at 0
        with pytest.raises(ValueError):
            PipelineSpec(3, boundaries=[0, 2]).stage_of(4)  # count mismatch


class TestDistributionDiagnostics:
    """Seeded fixture per E1xx/W10x code + a clean-bill counterpart."""

    def test_e101_batch_not_divisible(self):
        report = _mlp_conf().validate(batch_size=6, mesh="data=4")
        assert "DL4J-E101" in report.codes()
        assert not report.ok()
        assert "DL4J-E101" not in _mlp_conf().validate(
            batch_size=8, mesh="data=4").codes()

    def test_e102_absent_axis_in_sharding_rule(self):
        report = _mlp_conf().validate(mesh="data=4",
                                      sharding={r"/W$": (None, "model")})
        assert "DL4J-E102" in report.codes()
        assert "DL4J-E102" not in _mlp_conf().validate(
            mesh="data=4,model=1", sharding={r"/W$": (None, "model")}).codes()

    def test_e102_pipeline_axis_absent_or_mismatched(self):
        conf = _mlp_conf()
        r1 = conf.validate(mesh="data=4", pipeline=PipelineSpec(2))
        assert "DL4J-E102" in r1.codes()
        r2 = conf.validate(mesh="data=2,pipe=4", pipeline=PipelineSpec(2))
        assert "DL4J-E102" in r2.codes()

    def test_e102_axes_product_vs_declared_devices(self):
        # ISSUE 6: a mesh declaration that no longer matches the physical
        # device count (the elastic-shrink misconfiguration) is an E102
        from deeplearning4j_tpu.analysis.distribution import MeshSpec
        report = _mlp_conf().validate(
            mesh=MeshSpec({"data": 8}, devices=4))
        assert "DL4J-E102" in report.codes()
        assert "DL4J-E102" not in _mlp_conf().validate(
            mesh=MeshSpec({"data": 4}, devices=4)).codes()
        # DeviceMesh.spec() declares its own (consistent) device count
        from deeplearning4j_tpu.parallel import DeviceMesh
        spec = DeviceMesh.data_parallel().spec()
        assert spec.devices == 8
        assert "DL4J-E102" not in _mlp_conf().validate(mesh=spec).codes()

    def test_e103_tie_split_across_stages(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=8, tiedWith="emb"))
                .layer(DenseLayer(nOut=8))
                .layer(DenseLayer(nOut=8))
                .layer(OutputLayer(nOut=8, tiedWith="emb"))
                .setInputType(InputType.feedForward(8))
                .build())
        report = conf.validate(mesh="pipe=2,data=1",
                               pipeline=PipelineSpec(2))
        assert "DL4J-E103" in report.codes()
        # same tie group within one stage: clean
        one_stage = (_builder().list()
                     .layer(DenseLayer(nOut=8, tiedWith="emb"))
                     .layer(OutputLayer(nOut=8, tiedWith="emb"))
                     .layer(DenseLayer(nOut=8))
                     .layer(DenseLayer(nOut=8))
                     .setInputType(InputType.feedForward(8))
                     .build())
        r2 = analyze(one_stage, mesh="pipe=2,data=1",
                     pipeline=PipelineSpec(2))
        assert "DL4J-E103" not in r2.codes()
        assert "DL4J-E008" not in r2.codes() or True  # structure irrelevant

    def test_e104_hbm_budget(self):
        report = _wide_mlp().validate(mesh="data=8", hbm_gb=0.01)
        e104 = [d for d in report if d.code == "DL4J-E104"]
        assert e104 and "HBM budget" in DIAGNOSTIC_CODES["DL4J-E104"]
        assert "exceeds" in e104[0].message
        assert "DL4J-E104" not in _wide_mlp().validate(
            mesh="data=8", hbm_gb=16.0).codes()

    def test_w104_replicated_giant_with_idle_model_axis(self):
        report = _wide_mlp().validate(mesh="data=4,model=2")
        w104 = [d for d in report if d.code == "DL4J-W104"]
        assert w104 and "replicated" in w104[0].message
        # pure DP mesh: replication is the only layout — no warning
        assert "DL4J-W104" not in _wide_mlp().validate(mesh="data=8").codes()
        # sharded by rule: clean
        assert "DL4J-W104" not in _wide_mlp().validate(
            mesh="data=4,model=2",
            sharding={r"/W$": (None, "model")}).codes()

    def test_w105_pipeline_flop_imbalance(self):
        lop = (_builder().list()
               .layer(DenseLayer(nOut=2048, activation="relu"))   # heavy
               .layer(DenseLayer(nOut=8, activation="relu"))
               .layer(DenseLayer(nOut=8, activation="relu"))
               .layer(OutputLayer(nOut=2))
               .setInputType(InputType.feedForward(2048))
               .build())
        report = lop.validate(mesh="pipe=2,data=1",
                              pipeline=PipelineSpec(2))
        assert "DL4J-W105" in report.codes()
        balanced = (_builder().list()
                    .layer(DenseLayer(nOut=512, activation="relu"))
                    .layer(DenseLayer(nOut=512, activation="relu"))
                    .layer(DenseLayer(nOut=512, activation="relu"))
                    .layer(DenseLayer(nOut=512, activation="relu"))
                    .setInputType(InputType.feedForward(512))
                    .build())
        r2 = analyze(balanced, mesh="pipe=2,data=1",
                     pipeline=PipelineSpec(2))
        assert "DL4J-W105" not in r2.codes()

    def test_w106_sub_mxu_shard(self):
        rule = {r"DenseLayer/W$": (None, "model")}   # the 4096x4096 only
        report = _wide_mlp().validate(mesh="data=1,model=64", sharding=rule)
        w106 = [d for d in report if d.code == "DL4J-W106"]
        assert w106 and "MXU" in w106[0].message          # 4096/64 = 64 < 128
        # 4096/8 = 512 lanes per device: healthy
        assert "DL4J-W106" not in _wide_mlp().validate(
            mesh="data=1,model=8", sharding=rule).codes()

    def test_w106_non_divisible_shard(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=4096, activation="relu"))
                .layer(OutputLayer(nOut=2))
                .setInputType(InputType.feedForward(4100))
                .build())
        report = conf.validate(mesh="data=1,model=8",
                               sharding={r"/W$": ("model", None)})
        w106 = [d for d in report if d.code == "DL4J-W106"]
        assert w106 and "does not divide" in w106[0].message  # 4100 % 8

    def test_w107_collective_volume(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=16384, activation="relu"))
                .layer(OutputLayer(nOut=2))
                .setInputType(InputType.feedForward(16384))
                .build())
        report = conf.validate(mesh="data=8")
        w107 = [d for d in report if d.code == "DL4J-W107"]
        assert w107 and "allreduce" in w107[0].message
        assert "DL4J-W107" not in _mlp_conf().validate(mesh="data=8").codes()

    def test_mesh_replaces_w103_path(self):
        # with a declared mesh the divisibility finding is the E101 error,
        # not the softer W103 hint
        report = _mlp_conf().validate(batch_size=6, mesh="data=4")
        assert "DL4J-W103" not in report.codes()
        legacy = _mlp_conf().validate(batch_size=6, data_devices=4)
        assert "DL4J-W103" in legacy.codes()

    def test_graph_config_gets_distribution_lints(self):
        g = (_graph_builder()
             .addLayer("fc", DenseLayer(nOut=4096, nIn=4096), "in")
             .addLayer("out", OutputLayer(nOut=2), "fc")
             .setOutputs("out"))
        report = analyze(g.build(), mesh="data=4,model=2")
        assert "DL4J-W104" in report.codes()

    def test_parallel_wrapper_validate(self):
        from deeplearning4j_tpu.parallel.mesh import DeviceMesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        net = MultiLayerNetwork(_mlp_conf())
        pw = ParallelWrapper(net, mesh=DeviceMesh.data_parallel())
        report = pw.validate(batch_size=6)          # 6 % 8 != 0
        assert "DL4J-E101" in report.codes()
        assert "DL4J-E101" not in pw.validate(batch_size=16).codes()

    def test_zoo_clean_under_data8_mesh(self):
        # zero=True: a data-parallel training plan that shards the
        # updater state is the recommended shipping config (ISSUE 15) —
        # without it the big Adam-state models legitimately earn W109,
        # which TestDistributionAnalysis pins separately
        from deeplearning4j_tpu.models.zoo import all_zoo_models
        for name, net in all_zoo_models():
            report = analyze(net, mesh="data=8", zero=True)
            assert report.ok(warnings_as_errors=True), \
                f"{name} not clean under data=8:\n{report.format()}"

    def test_zoo_w109_without_zero_declaration(self):
        # the inverse pin: at least the heavyweight zoo configs DO warn
        # when a data=8 mesh trains with replicated optimizer state
        from deeplearning4j_tpu.models.zoo import VGG16
        report = analyze(VGG16().conf_builder(), mesh="data=8")
        assert "DL4J-W109" in report.codes()


class TestSuppressionConfig:
    def test_validate_suppress(self):
        conf = _mlp_conf(hidden=300)                 # seeds W101
        assert "DL4J-W101" in conf.validate().codes()
        report = conf.validate(suppress=["DL4J-W101"])
        assert "DL4J-W101" not in report.codes()
        # short spelling works too
        assert "DL4J-W101" not in conf.validate(suppress=["w101"]).codes()

    def test_validate_severity_override(self):
        conf = _mlp_conf(hidden=300)
        report = conf.validate(severity_overrides={"W101": "error"})
        w = [d for d in report if d.code == "DL4J-W101"]
        assert w and w[0].severity is Severity.ERROR
        assert not report.ok()
        down = conf.validate(severity_overrides={"W101": Severity.INFO})
        assert down.ok(warnings_as_errors=True)

    def test_unknown_code_rejected(self):
        with pytest.raises(ValueError, match="unknown diagnostic code"):
            _mlp_conf().validate(suppress=["W999"])
        with pytest.raises(ValueError, match="unknown severity"):
            _mlp_conf().validate(severity_overrides={"W101": "loud"})

    def test_strict_init_honors_suppression_semantics(self):
        # an upgraded warning fails strict init; a suppressed error passes
        conf = _mlp_conf(hidden=300)
        report = conf.validate(severity_overrides={"W101": "error"})
        with pytest.raises(ModelValidationError):
            report.raise_if_errors()

    def test_cli_suppress_and_severity(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        # W101 model fails by default, passes when suppressed
        import tests.test_analysis as self_mod          # noqa: F401
        rc_plain = main(["tests.test_analysis:_W101_FIXTURE"])
        assert rc_plain == 1
        rc_sup = main(["tests.test_analysis:_W101_FIXTURE",
                       "--suppress", "W101"])
        assert rc_sup == 0
        rc_info = main(["tests.test_analysis:_W101_FIXTURE",
                        "--severity", "W101=info"])
        assert rc_info == 0
        capsys.readouterr()


#: module-level fixture for the CLI suppression test (resolved by the
#: module:attr target syntax; callables are called with no args)
def _W101_FIXTURE():
    return _mlp_conf(hidden=300)


class TestCliMesh:
    def test_zoo_clean_under_mesh_flag(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        # --zero: see test_zoo_clean_under_data8_mesh (W109 otherwise)
        assert main(["--zoo", "--mesh", "data=8", "--zero"]) == 0
        assert "16 model(s) linted: 16 clean" in capsys.readouterr().out

    def test_mesh_flag_fails_bad_batch(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        rc = main(["LeNet", "--mesh", "data=8", "--batch-size", "6"])
        assert rc == 1
        assert "DL4J-E101" in capsys.readouterr().out

    def test_hbm_flag(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        rc = main(["VGG16", "--mesh", "data=8", "--hbm-gb", "0.01"])
        assert rc == 1
        assert "DL4J-E104" in capsys.readouterr().out


class TestSameDiffLint:
    def _mlp_graph(self):
        import jax.numpy as jnp                        # noqa: F401
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 3))
        labels = sd.placeHolder("labels", shape=(None, 2))
        rng = np.random.RandomState(0)
        w = sd.var("w", rng.randn(3, 2))
        b = sd.var("b", np.zeros(2))
        z = sd.nn.linear(x, w, b, name="z")
        sd.loss.softmaxCrossEntropy(labels, z, name="loss")
        sd.setLossVariables("loss")
        return sd

    def test_clean_bill(self):
        report = self._mlp_graph().validate()
        assert report.ok(warnings_as_errors=True), report.format()
        assert report.subject == "SameDiff"

    def test_e151_undefined_input(self):
        sd = self._mlp_graph()
        sd._nodes[0].inputs[0] = "ghost"    # simulate a corrupted load
        report = sd.validate()
        assert "DL4J-E151" in report.codes()

    def test_e152_matmul_conflict(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        a = sd.var("a", np.zeros((3, 4)))
        b = sd.var("b", np.zeros((5, 6)))
        a.mmul(b)
        report = sd.validate()
        e = [d for d in report if d.code == "DL4J-E152"]
        assert e and "contracting dims" in e[0].message

    def test_e152_broadcast_conflict(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        p = sd.var("p", np.zeros((3, 4)))
        q = sd.var("q", np.zeros((3, 5)))
        p.add(q)
        assert "DL4J-E152" in sd.validate().codes()

    def test_e153_bad_loss_variable(self):
        sd = self._mlp_graph()
        sd.setLossVariables("loss", "no_such_var")
        assert "DL4J-E153" in sd.validate().codes()

    def test_w151_dangling_placeholder(self):
        sd = self._mlp_graph()
        sd.placeHolder("ghost", shape=(None, 3))
        report = sd.validate()
        w = [d for d in report if d.code == "DL4J-W151"]
        assert w and "ghost" in w[0].location

    def test_w152_unused_variable(self):
        sd = self._mlp_graph()
        sd.var("dead", np.zeros((4, 4)))
        report = sd.validate()
        w = [d for d in report if d.code == "DL4J-W152"]
        assert w and "dead" in w[0].location
        # ancestors of the loss are NOT flagged
        assert not any("'w'" in d.location for d in w)

    def test_w153_training_config_without_loss(self):
        from deeplearning4j_tpu.autodiff.samediff import (SameDiff,
                                                          TrainingConfig)
        sd = SameDiff.create()
        sd.var("v", np.zeros((2, 2)))
        sd.setTrainingConfig(TrainingConfig())
        assert "DL4J-W153" in sd.validate().codes()
        sd2 = self._mlp_graph()
        sd2.setTrainingConfig(TrainingConfig())
        assert "DL4J-W153" not in sd2.validate().codes()

    def test_unknown_ops_degrade_gracefully(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 3, 8))
        y = sd.cnn.conv1d(x, sd.var("w", np.zeros((4, 3, 3))))
        (y + y).sum()
        report = sd.validate()                 # no rule for conv1d: no lie
        assert "DL4J-E152" not in report.codes()

    def test_suppress_applies_to_samediff(self):
        sd = self._mlp_graph()
        sd.var("dead", np.zeros((4, 4)))
        assert "DL4J-W152" not in sd.validate(
            suppress=["W152"]).codes()


class TestTbpttFitWiring:
    """fit() honors backpropType('tbptt')/tBPTTLength — equivalent to
    manual fitTBPTT segment fits (clears PR 3's W002 'declared but
    unwired' caveat)."""

    def _net(self, tbptt):
        b = (_builder(Sgd(0.05)).list()
             .layer(LSTM(nOut=6))
             .layer(RnnOutputLayer(nOut=2, lossFunction="mcxent"))
             .setInputType(InputType.recurrent(3, 12)))
        if tbptt:
            b = b.backpropType("tbptt", 4)
        return MultiLayerNetwork(b.build()).init(seed=11)

    def _seq_data(self):
        rng = np.random.RandomState(0)
        feats = rng.rand(5, 3, 12).astype(np.float32)
        labs = np.zeros((5, 2, 12), np.float32)
        labs[::2, 0] = 1.0
        labs[1::2, 1] = 1.0
        return DataSet(feats, labs)

    def test_fit_equals_manual_segment_fits(self):
        ds = self._seq_data()
        auto = self._net(True)
        auto.fit(ds, epochs=2)
        manual = self._net(False)
        for _ in range(2):
            manual.fitTBPTT(ds, 4)
        assert auto._iteration == manual._iteration == 6   # 3 seg x 2 ep
        np.testing.assert_array_equal(np.asarray(auto.params()),
                                      np.asarray(manual.params()))

    def test_fit_differs_from_standard_backprop(self):
        ds = self._seq_data()
        tb = self._net(True)
        tb.fit(ds, epochs=1)
        std = self._net(False)
        std.fit(ds, epochs=1)
        assert tb._iteration == 3 and std._iteration == 1
        assert not np.array_equal(np.asarray(tb.params()),
                                  np.asarray(std.params()))

    def test_non_sequence_batch_falls_back(self):
        conf = (_builder(Sgd(0.1)).list()
                .layer(DenseLayer(nOut=8, activation="relu"))
                .layer(OutputLayer(nOut=2))
                .setInputType(InputType.feedForward(4))
                .backpropType("tbptt", 4)
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(0)
        net.fit(DataSet(rng.rand(6, 4).astype(np.float32), _one_hot(6)))
        assert net._iteration == 1              # plain step, no segments


class TestPureStaticDistribution:
    """Distribution + SameDiff passes run with jax BLOCKED: both operate
    on duck-typed declared shapes only."""

    def test_passes_run_with_jax_blocked(self):
        code = (
            "import sys\n"
            "sys.modules['jax'] = None\n"
            "sys.modules['jax.numpy'] = None\n"
            "from types import SimpleNamespace as NS\n"
            "from deeplearning4j_tpu.analysis import (MeshSpec,\n"
            "    PipelineSpec, analyze_samediff)\n"
            "from deeplearning4j_tpu.analysis.distribution import "
            "lint_entries\n"
            "class FakeLayer:\n"
            "    name = 'fc'\n"
            "    tied_with = None\n"
            "    def param_shapes(self):\n"
            "        return {'W': (4096, 50000), 'b': (50000,)}\n"
            "entries = [('layer 0 (FakeLayer)', FakeLayer(), None, None)]\n"
            "mesh = MeshSpec({'data': 8, 'model': 2}, hbm_gb=0.05)\n"
            "codes = {d.code for d in lint_entries(entries, mesh, 6,\n"
            "                                      'float32')}\n"
            "assert 'DL4J-E101' in codes, codes\n"
            "assert 'DL4J-E104' in codes, codes\n"
            "assert 'DL4J-W104' in codes, codes\n"
            "class Arr:\n"
            "    def __init__(self, shape):\n"
            "        self.shape = shape\n"
            "        self.dtype = 'float32'\n"
            "class Node:\n"
            "    def __init__(self, op, ins, outs):\n"
            "        self.op, self.inputs, self.outputs = op, ins, outs\n"
            "        self.attrs = {}\n"
            "sd = NS(_nodes=[Node('matmul', ['a', 'b'], ['c'])],\n"
            "        _placeholders={}, _constants={},\n"
            "        _variables={'a': Arr((3, 4)), 'b': Arr((5, 6))},\n"
            "        _loss_variables=[], training_config=None)\n"
            "r = analyze_samediff(sd)\n"
            "assert 'DL4J-E152' in [d.code for d in r], r.format()\n"
            "print('PURE-STATIC-DIST-OK')\n")
        proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "PURE-STATIC-DIST-OK" in proc.stdout

    def test_new_code_families_documented(self):
        for code in ("DL4J-E101", "DL4J-E102", "DL4J-E103", "DL4J-E104",
                     "DL4J-W104", "DL4J-W105", "DL4J-W106", "DL4J-W107",
                     "DL4J-E151", "DL4J-E152", "DL4J-E153", "DL4J-W151",
                     "DL4J-W152", "DL4J-W153",
                     "DL4J-E161", "DL4J-E162", "DL4J-E163", "DL4J-W161",
                     "DL4J-W162", "DL4J-W163"):
            assert code in DIAGNOSTIC_CODES


class TestReviewRegressions:
    """Pins for the review findings on the distribution/samediff passes."""

    def test_unknown_nonbatch_placeholder_dim_stays_unknown(self):
        # (None, None) placeholder: only dim 0 is the batch — a free
        # feature dim must not fabricate an E152 against W's rows
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, None))
        w = sd.var("w", np.zeros((3, 2)))
        b = sd.var("b", np.zeros(2))
        sd.nn.linear(x, w, b, name="z")
        assert "DL4J-E152" not in sd.validate(batch_size=4).codes()

    def test_e104_budgets_the_heaviest_pipeline_stage(self):
        conf = (_builder().list()                      # 64 MiB per layer
                .layer(DenseLayer(nOut=4096, activation="relu"))
                .layer(DenseLayer(nOut=4096, activation="relu"))
                .setInputType(InputType.feedForward(4096))
                .build())
        mesh = "pipe=2,data=1"
        # total 128 MiB, but each stage holds 64 MiB: a 0.1 GiB budget
        # passes under the pipeline split and fails without it
        ok = analyze(conf, mesh=mesh, pipeline=PipelineSpec(2),
                     hbm_gb=0.1)
        assert "DL4J-E104" not in ok.codes(), ok.format()
        flat = analyze(conf, mesh="data=1", hbm_gb=0.1)
        assert "DL4J-E104" in flat.codes()
        tight = analyze(conf, mesh=mesh, pipeline=PipelineSpec(2),
                        hbm_gb=0.05)
        e = [d for d in tight if d.code == "DL4J-E104"]
        assert e and "pipeline stage" in e[0].location

    def test_w107_clears_when_tensor_is_sharded(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=16384, activation="relu"))
                .layer(OutputLayer(nOut=2))
                .setInputType(InputType.feedForward(16384))
                .build())
        assert "DL4J-W107" in conf.validate(mesh="data=8,model=4").codes()
        sharded = conf.validate(mesh="data=8,model=4",
                                sharding={r"DenseLayer/W$": (None, "model")})
        assert "DL4J-W107" not in sharded.codes(), sharded.format()

    def test_hbm_without_mesh_is_an_error_not_a_noop(self):
        with pytest.raises(ValueError, match="mesh"):
            _mlp_conf().validate(hbm_gb=0.001)

    def test_samediff_mesh_kwargs_run_distribution_lints(self):
        # ISSUE 18 flipped this pin: mesh= on a recorded graph now runs
        # the distribution family instead of raising
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 4))
        w = sd.var("w", np.zeros((4, 2), np.float32))
        x.mmul(w)
        report = sd.validate(batch_size=12, mesh="data=8")
        assert "DL4J-E101" in report.codes(), report.format()
        # input_pipeline stays native-config-only
        with pytest.raises(ValueError, match="input_pipeline"):
            sd.validate(input_pipeline="workers=8,batch=256")

    def test_cli_rejects_unknown_codes_cleanly(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        with pytest.raises(SystemExit) as ei:
            main(["LeNet", "--suppress", "W999"])
        assert ei.value.code == 2                      # argparse usage error
        assert "unknown diagnostic code" in capsys.readouterr().err
        with pytest.raises(SystemExit):
            main(["LeNet", "--severity", "W101=loud"])
        with pytest.raises(SystemExit):
            main(["LeNet", "--hbm-gb", "1"])           # no --mesh
        capsys.readouterr()


# --------------------------------------------------------------- ISSUE 8
def _lint_src(tmp_path, source, name="fixture.py", **kw):
    """Write a source fixture and run the concurrency analyzer on it."""
    from deeplearning4j_tpu.analysis.concurrency import analyze_concurrency
    p = tmp_path / name
    p.write_text(source)
    return analyze_concurrency(str(p), **kw)


_E201_BAD = """
import threading

class Worker:
    def __init__(self):
        self.state = "idle"
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        self.state = "running"

    def close(self):
        self._thread.join()
        self.state = "closed"
"""

_E201_CLEAN = """
import threading

class Worker:
    def __init__(self):
        self.state = "idle"
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        with self._lock:
            self.state = "running"

    def close(self):
        self._thread.join()
        with self._lock:
            self.state = "closed"
"""

_E202_BAD = """
import threading

class Stats:
    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._serve, daemon=True)

    def _serve(self):
        self.count += 1

    def snapshot(self):
        return self.count

    def close(self):
        self._thread.join()
"""

_E203_BAD = """
import threading

class A:
    def __init__(self, b: "B"):
        self._lock = threading.Lock()
        self.b = b

    def poke(self):
        with self._lock:
            self.b.poke_back()

    def locked_op(self):
        with self._lock:
            pass

class B:
    def __init__(self, a: "A"):
        self._lock = threading.Lock()
        self.a = a

    def poke_back(self):
        with self._lock:
            pass

    def reverse(self):
        with self._lock:
            self.a.locked_op()
"""

_W210_BAD = """
import time

class Retry:
    def expired(self, deadline):
        return time.time() > deadline

    def backoff(self, started):
        return time.time() - started
"""

_W211_BAD = """
import threading

class Q:
    def __init__(self):
        self._cond = threading.Condition()
        self.items = []

    def take(self):
        with self._cond:
            self._cond.wait(1.0)
            return self.items.pop()
"""

_W211_CLEAN = """
import threading

class Q:
    def __init__(self):
        self._cond = threading.Condition()
        self.items = []

    def take(self):
        with self._cond:
            while not self.items:
                self._cond.wait(1.0)
            return self.items.pop()
"""

_W212_BAD = """
import threading

class Server:
    def __init__(self):
        self._worker = threading.Thread(target=self._serve, daemon=True)
        self._worker.start()

    def _serve(self):
        pass

    def close(self):
        pass
"""

_W213_BAD = """
import threading

class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = None
        self._thread = threading.Thread(target=self._refresh, daemon=True)

    def _refresh(self):
        with self._lock:
            pass

    def table(self):
        if self._table is None:
            self._table = {}
        return self._table

    def close(self):
        self._thread.join()
"""

_W213_CLEAN = """
import threading

class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = None
        self._thread = threading.Thread(target=self._refresh, daemon=True)

    def _refresh(self):
        with self._lock:
            pass

    def table(self):
        with self._lock:
            if self._table is None:
                self._table = {}
            return self._table

    def close(self):
        self._thread.join()
"""


class TestConcurrencyDiagnostics:
    """ISSUE 8: one seeded bad fixture + clean bill per E2xx/W21x code."""

    def test_e201_unguarded_cross_thread_mutation(self, tmp_path):
        report = _lint_src(tmp_path, _E201_BAD)
        assert report.codes().count("DL4J-E201") == 2
        assert "state" in report.errors()[0].message

    def test_e201_clean_when_guarded(self, tmp_path):
        report = _lint_src(tmp_path, _E201_CLEAN, name="clean.py")
        assert report.codes() == []

    def test_e202_read_modify_write(self, tmp_path):
        report = _lint_src(tmp_path, _E202_BAD)
        assert "DL4J-E202" in report.codes()
        assert "lost" in report.format() or "loses" in report.format()

    def test_e202_clean_under_lock(self, tmp_path):
        clean = _E202_BAD.replace(
            "        self.count += 1",
            "        with self._lock:\n            self.count += 1")
        report = _lint_src(tmp_path, clean, name="clean.py")
        assert report.codes() == []

    def test_e203_lock_order_cycle(self, tmp_path):
        report = _lint_src(tmp_path, _E203_BAD)
        assert "DL4J-E203" in report.codes()
        assert "A._lock" in report.format()
        # the cycle must anchor to a real source line (line 0 is
        # untriageable and un-noqa-able)
        for d in report:
            if d.code == "DL4J-E203":
                assert ":0" not in d.location, d.location
        assert "B._lock" in report.format()

    def test_e203_not_shadowed_by_same_named_class(self, tmp_path):
        # an unrelated same-named class in an earlier-scanned file must
        # not shadow the real one out of the lock graph
        from deeplearning4j_tpu.analysis.concurrency import \
            analyze_concurrency
        (tmp_path / "a_first.py").write_text(
            "class A:\n    def m(self):\n        pass\n"
            "class B:\n    def m(self):\n        pass\n")
        (tmp_path / "b_cycle.py").write_text(_E203_BAD)
        report = analyze_concurrency(str(tmp_path))
        assert "DL4J-E203" in report.codes()

    def test_e202_inside_match_statement(self, tmp_path):
        src = _E202_BAD.replace(
            "        self.count += 1",
            "        match self.count:\n"
            "            case _:\n"
            "                self.count += 1")
        report = _lint_src(tmp_path, src)
        assert "DL4J-E202" in report.codes()

    def test_e203_clean_when_one_order(self, tmp_path):
        # B.reverse now calls A outside its own lock: edges stay A->B only
        clean = _E203_BAD.replace(
            "    def reverse(self):\n"
            "        with self._lock:\n"
            "            self.a.locked_op()",
            "    def reverse(self):\n"
            "        self.a.locked_op()")
        assert "with self._lock:\n            self.a" not in clean
        report = _lint_src(tmp_path, clean, name="clean.py")
        assert report.codes() == []

    def test_w210_wall_clock_deadline(self, tmp_path):
        report = _lint_src(tmp_path, _W210_BAD)
        assert report.codes().count("DL4J-W210") == 2

    def test_w210_clean_monotonic_and_timestamps(self, tmp_path):
        clean = _W210_BAD.replace("time.time()", "time.monotonic()")
        # a recorded wall-clock timestamp (no arithmetic) stays legal
        clean += "\n\ndef stamp(record):\n"
        clean += "    record['timestamp'] = time.time()\n"
        report = _lint_src(tmp_path, clean, name="clean.py")
        assert report.codes() == []

    def test_w210_attr_assigned_then_subtracted(self, tmp_path):
        src = ("import time\n\n"
               "class T:\n"
               "    def start(self):\n"
               "        self.t0 = time.time()\n"
               "    def elapsed(self):\n"
               "        return time.time() - self.t0\n")
        report = _lint_src(tmp_path, src)
        assert "DL4J-W210" in report.codes()

    def test_w211_wait_without_predicate_loop(self, tmp_path):
        report = _lint_src(tmp_path, _W211_BAD)
        assert "DL4J-W211" in report.codes()

    def test_w211_clean_in_while(self, tmp_path):
        report = _lint_src(tmp_path, _W211_CLEAN, name="clean.py")
        assert "DL4J-W211" not in report.codes()

    def test_w212_thread_never_joined(self, tmp_path):
        report = _lint_src(tmp_path, _W212_BAD)
        assert "DL4J-W212" in report.codes()

    def test_w212_clean_with_join(self, tmp_path):
        clean = _W212_BAD.replace("    def close(self):\n        pass",
                                  "    def close(self):\n"
                                  "        self._worker.join(timeout=5)")
        report = _lint_src(tmp_path, clean, name="clean.py")
        assert "DL4J-W212" not in report.codes()

    def test_w213_unlocked_lazy_init(self, tmp_path):
        report = _lint_src(tmp_path, _W213_BAD)
        assert "DL4J-W213" in report.codes()

    def test_w213_clean_checked_under_lock(self, tmp_path):
        report = _lint_src(tmp_path, _W213_CLEAN, name="clean.py")
        assert "DL4J-W213" not in report.codes()

    def test_inline_noqa_suppresses(self, tmp_path):
        src = _E202_BAD.replace("        self.count += 1",
                                "        self.count += 1  # dl4j: noqa=E202")
        report = _lint_src(tmp_path, src)
        assert "DL4J-E202" not in report.codes()

    def test_noqa_tolerates_spaces_and_trailing_prose(self, tmp_path):
        # 'noqa = E202' must suppress E202 (and ONLY E202), and trailing
        # words after the code list must not corrupt the code set
        for comment in ("# dl4j: noqa = E202",
                        "# dl4j: noqa=E202 reviewed, see PR 8"):
            src = _E202_BAD.replace(
                "        self.count += 1",
                f"        self.count += 1  {comment}")
            report = _lint_src(tmp_path, src)
            assert "DL4J-E202" not in report.codes(), comment

    def test_noqa_with_garbage_codes_suppresses_nothing(self, tmp_path):
        src = _E202_BAD.replace(
            "        self.count += 1",
            "        self.count += 1  # dl4j: noqa=notacode")
        report = _lint_src(tmp_path, src)
        assert "DL4J-E202" in report.codes()

    def test_unparseable_file_is_e299_not_e201(self, tmp_path):
        report = _lint_src(tmp_path, "def broken(:\n")
        assert "DL4J-E299" in report.codes()
        assert "DL4J-E201" not in report.codes()
        # grandfathering a real finding family must NOT hide syntax errors
        report = _lint_src(tmp_path, "def broken(:\n", suppress=["E201"])
        assert "DL4J-E299" in report.codes()

    def test_suppress_and_severity_config(self, tmp_path):
        report = _lint_src(tmp_path, _E202_BAD, suppress=["E202"])
        assert "DL4J-E202" not in report.codes()
        report = _lint_src(tmp_path, _W212_BAD, name="w.py",
                           severity_overrides={"W212": "error"})
        codes = {d.code: d.severity for d in report}
        assert codes["DL4J-W212"] is Severity.ERROR

    def test_unthreaded_unlocked_class_is_exempt(self, tmp_path):
        # plain single-threaded mutable state must not be flagged
        src = ("class Plain:\n"
               "    def __init__(self):\n"
               "        self.count = 0\n"
               "    def inc(self):\n"
               "        self.count += 1\n")
        report = _lint_src(tmp_path, src, name="clean.py")
        assert report.codes() == []

    def test_new_codes_documented(self):
        for code in ("DL4J-E201", "DL4J-E202", "DL4J-E203", "DL4J-W210",
                     "DL4J-W211", "DL4J-W212", "DL4J-W213", "DL4J-E299"):
            assert code in DIAGNOSTIC_CODES


class TestConcurrencyCli:
    def test_cli_path_target_bad_fixture(self, tmp_path, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        p = tmp_path / "bad.py"
        p.write_text(_E202_BAD)
        assert main(["--concurrency", str(p)]) == 1
        assert "DL4J-E202" in capsys.readouterr().out

    def test_cli_module_target_repo_clean(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["--concurrency", "deeplearning4j_tpu.serving"]) == 0
        assert "clean" in capsys.readouterr().out

    def test_cli_bad_target_is_clean_usage_error(self, capsys):
        # a typo'd module and an unlintable builtin must be one-line
        # argparse errors (exit 2), not raw tracebacks
        from deeplearning4j_tpu.analysis.__main__ import main
        for target in ("definitely_not_a_module_xyz", "sys"):
            with pytest.raises(SystemExit) as exc:
                main(["--concurrency", target])
            assert exc.value.code == 2
            assert "--concurrency" in capsys.readouterr().err

    def test_cli_suppress_applies(self, tmp_path, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        p = tmp_path / "bad.py"
        p.write_text(_W212_BAD)
        assert main(["--concurrency", str(p), "--suppress", "W212"]) == 0
        capsys.readouterr()

    def test_cli_rejects_mixed_targets(self, tmp_path, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        p = tmp_path / "bad.py"
        p.write_text(_W212_BAD)
        with pytest.raises(SystemExit):
            main(["--concurrency", str(p), "LeNet"])
        capsys.readouterr()


class TestConcurrencySelfLint:
    """The repo lints itself clean — the gate that keeps the E2xx bug
    class out of the package from here on (ISSUE 8 acceptance)."""

    def _lint_mod(self):
        spec = importlib.util.spec_from_file_location(
            "repo_lint", REPO / "tools" / "lint.py")
        lint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lint)
        return lint

    def test_package_concurrency_clean(self, capsys):
        lint = self._lint_mod()
        rc = lint.run_concurrency()
        out = capsys.readouterr().out
        assert rc == 0, f"concurrency self-lint found issues:\n{out}"

    def test_pyproject_suppressions_parse(self):
        lint = self._lint_mod()
        assert isinstance(lint._pyproject_concurrency_suppress(), list)

    def test_pyproject_multiline_suppress_array(self, tmp_path):
        lint = self._lint_mod()
        (tmp_path / "pyproject.toml").write_text(
            "[tool.dl4j.concurrency]\n"
            "suppress = [\n"
            '    "W212",  # see [tool.other] "docs"]\n'
            '    "E201",\n'
            "]\n")
        old = lint.REPO
        try:
            lint.REPO = tmp_path
            assert lint._pyproject_concurrency_suppress() == ["W212", "E201"]
        finally:
            lint.REPO = old

    def test_typod_suppress_code_is_clean_usage_error(self, tmp_path, capsys):
        lint = self._lint_mod()
        (tmp_path / "pyproject.toml").write_text(
            "[tool.dl4j.concurrency]\n"
            'suppress = ["NOTACODE1"]\n')
        (tmp_path / "empty.py").write_text("x = 1\n")
        old = lint.REPO
        try:
            lint.REPO = tmp_path
            rc = lint.run_concurrency(["empty.py"])
        finally:
            lint.REPO = old
        assert rc == 1
        assert "bad suppress config" in capsys.readouterr().out

    def test_pyproject_suppressions_survive_other_keys(self, tmp_path):
        # other keys, comments with '[', and a following section must not
        # silently defeat the scoped parse
        lint = self._lint_mod()
        (tmp_path / "pyproject.toml").write_text(
            "[tool.dl4j.concurrency]\n"
            "# see [analysis] docs\n"
            'paths = ["deeplearning4j_tpu"]\n'
            'suppress = ["W212", "E201"]\n'
            "[tool.other]\n"
            'suppress = ["W999"]\n')
        old = lint.REPO
        try:
            lint.REPO = tmp_path
            assert lint._pyproject_concurrency_suppress() == ["W212", "E201"]
        finally:
            lint.REPO = old

    def test_gate_fails_on_seeded_regression(self, tmp_path, capsys):
        # the gate must actually have teeth: a bad file inside the tree
        # it lints turns the exit code red
        lint = self._lint_mod()
        bad = tmp_path / "racy.py"
        bad.write_text(_E202_BAD)
        assert lint.run_concurrency([bad.relative_to(REPO)
                                     if bad.is_relative_to(REPO)
                                     else str(bad)]) == 1
        capsys.readouterr()


class TestPureStaticConcurrency:
    """The concurrency pass runs with jax BLOCKED — it reads source
    text, never imports the target (matching the distribution/samediff
    pins)."""

    def test_runs_with_jax_blocked(self):
        code = (
            "import sys\n"
            "sys.modules['jax'] = None\n"
            "sys.modules['jax.numpy'] = None\n"
            "from deeplearning4j_tpu.analysis.concurrency import "
            "analyze_concurrency\n"
            "r = analyze_concurrency('deeplearning4j_tpu/serving')\n"
            "assert r.codes() == [], r.format()\n"
            # and the full-package run stays clean too — over files that
            # themselves import jax (never executed, only parsed)
            "r = analyze_concurrency('deeplearning4j_tpu')\n"
            "assert r.codes() == [], r.format()\n"
            "print('PURE-STATIC-CONCURRENCY-OK')\n")
        proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "PURE-STATIC-CONCURRENCY-OK" in proc.stdout


class TestInputPipelineLint:
    """DL4J-W108: can this host feed this chip (analysis/pipeline.py)."""

    def _conv_conf(self):
        return (NeuralNetConfiguration.Builder().list()
                .layer(ConvolutionLayer(nOut=64, kernelSize=(3, 3)))
                .layer(ConvolutionLayer(nOut=128, kernelSize=(3, 3)))
                .layer(DenseLayer(nOut=64, activation="relu"))
                .layer(OutputLayer(nOut=8))
                .setInputType(InputType.convolutional(64, 64, 3))
                .build())

    def test_starved_pipeline_flags_w108(self):
        from deeplearning4j_tpu.analysis import InputPipelineSpec, analyze
        spec = InputPipelineSpec(workers=1, batch_size=256,
                                 decode_ms_per_img=50.0, h2d_mbps=6.2,
                                 dtype="float32")
        report = analyze(self._conv_conf(), input_pipeline=spec)
        w108 = [d for d in report.diagnostics if d.code == "DL4J-W108"]
        assert len(w108) == 1
        assert "cannot feed this chip" in w108[0].message
        assert "uint8" in w108[0].fix_hint      # float32 link: suggest bytes

    def test_fed_pipeline_clean(self):
        from deeplearning4j_tpu.analysis import InputPipelineSpec, analyze
        spec = InputPipelineSpec(workers=256, batch_size=256,
                                 decode_ms_per_img=1.0, h2d_mbps=100000,
                                 dtype="uint8")
        report = analyze(self._conv_conf(), input_pipeline=spec)
        assert "DL4J-W108" not in [d.code for d in report.diagnostics]

    def test_measured_device_rate_overrides_estimate(self):
        from deeplearning4j_tpu.analysis import InputPipelineSpec, analyze
        # decode bound 2000 img/s: above a measured 1000 img/s device
        # rate (clean), below a measured 10000 img/s one (W108)
        base = dict(workers=2, batch_size=64, decode_ms_per_img=1.0,
                    dtype="uint8")
        clean = analyze(self._conv_conf(), input_pipeline=InputPipelineSpec(
            device_img_per_sec=1000, **base))
        assert "DL4J-W108" not in [d.code for d in clean.diagnostics]
        hot = analyze(self._conv_conf(), input_pipeline=InputPipelineSpec(
            device_img_per_sec=10000, **base))
        assert "DL4J-W108" in [d.code for d in hot.diagnostics]

    def test_spec_parse_and_coerce(self):
        from deeplearning4j_tpu.analysis import InputPipelineSpec
        s = InputPipelineSpec.parse(
            "workers=8,batch=256,decode_ms=1.3,h2d_mbps=6.2,hw=224,"
            "dtype=uint8,mfu=0.25")
        assert (s.workers, s.batch_size, s.height, s.width) == \
            (8, 256, 224, 224)
        assert s.assumed_mfu == 0.25
        assert InputPipelineSpec.coerce(s) is s
        d = InputPipelineSpec.coerce({"workers": 2, "batch_size": 32})
        assert d.workers == 2
        with pytest.raises(ValueError, match="known keys"):
            InputPipelineSpec.parse("wrkrs=8")
        with pytest.raises(ValueError, match="workers"):
            InputPipelineSpec.parse("batch=32")

    def test_w108_suppressible_and_documented(self):
        from deeplearning4j_tpu.analysis import InputPipelineSpec, analyze
        assert "DL4J-W108" in DIAGNOSTIC_CODES
        spec = InputPipelineSpec(workers=1, batch_size=256,
                                 decode_ms_per_img=50.0)
        report = analyze(self._conv_conf(), input_pipeline=spec,
                         suppress=["W108"])
        assert "DL4J-W108" not in [d.code for d in report.diagnostics]

    def test_cli_pipeline_flag(self, capsys, tmp_path, monkeypatch):
        mod = tmp_path / "feedmodel.py"
        mod.write_text(
            "from deeplearning4j_tpu.nn.config import (InputType,\n"
            "    NeuralNetConfiguration)\n"
            "from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,\n"
            "    DenseLayer, OutputLayer)\n"
            "conf = (NeuralNetConfiguration.Builder().list()\n"
            "        .layer(ConvolutionLayer(nOut=64, kernelSize=(3, 3)))\n"
            "        .layer(DenseLayer(nOut=64, activation='relu'))\n"
            "        .layer(OutputLayer(nOut=8))\n"
            "        .setInputType(InputType.convolutional(64, 64, 3))\n"
            "        .build())\n")
        monkeypatch.syspath_prepend(str(tmp_path))
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["feedmodel:conf", "--pipeline",
                     "workers=1,batch=256,decode_ms=50.0"]) == 1
        assert "DL4J-W108" in capsys.readouterr().out
        # typo'd spec: clean usage error, not a traceback
        with pytest.raises(SystemExit) as ei:
            main(["feedmodel:conf", "--pipeline", "wrkrs=1"])
        assert ei.value.code == 2

    def test_graph_config_needs_measured_rate(self):
        """Graph configs have no jax-free FLOP propagation: without a
        measured device rate the lint stays silent instead of guessing."""
        from deeplearning4j_tpu.analysis import InputPipelineSpec, analyze
        conf = (NeuralNetConfiguration.Builder().graphBuilder()
                .addInputs("in")
                .addLayer("c", ConvolutionLayer(nOut=8, kernelSize=(3, 3)),
                          "in")
                .addLayer("d", DenseLayer(nOut=16, activation="relu"), "c")
                .addLayer("out", OutputLayer(nOut=4), "d")
                .setOutputs("out")
                .setInputTypes(InputType.convolutional(16, 16, 3)))
        spec = InputPipelineSpec(workers=1, batch_size=64,
                                 decode_ms_per_img=50.0, height=16,
                                 width=16)
        r = analyze(conf, input_pipeline=spec)
        assert "DL4J-W108" not in [d.code for d in r.diagnostics]
        spec2 = InputPipelineSpec(workers=1, batch_size=64,
                                  decode_ms_per_img=50.0, height=16,
                                  width=16, device_img_per_sec=10000)
        r2 = analyze(conf, input_pipeline=spec2)
        assert "DL4J-W108" in [d.code for d in r2.diagnostics]


# ------------------------------------------------- numerics lints (ISSUE 11)
class TestNumericsDiagnostics:
    """E301-E303 / W301-W303: one seeded misconfiguration AND one clean
    bill per code, under explicit policies and DataRangeSpec input
    declarations."""

    def _mlp(self, updater=None, **layer_kw):
        from deeplearning4j_tpu.nn.layers import LossLayer  # noqa: F401
        return (_builder(updater).list()
                .layer(DenseLayer(nOut=16, activation="relu", **layer_kw))
                .layer(OutputLayer(nOut=3, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(8))
                .build())

    def test_e301_low_precision_updater_state(self):
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        conf = self._mlp(updater=Adam(1e-3))
        pol = PrecisionPolicy("float16", params="float16", loss_scale=1024)
        report = analyze(conf, policy=pol)
        assert "DL4J-E301" in report.codes()
        assert not report.ok()
        # fp32 masters (the default coercion): clean
        assert "DL4J-E301" not in analyze(conf, policy="fp16",
                                          suppress=["E303"]).codes()
        # stateless Sgd tolerates low-precision state declarations
        assert "DL4J-E301" not in analyze(
            self._mlp(), policy=pol).codes()

    def test_e301_contradicting_layer_override(self):
        conf = self._mlp(dataType="float16")
        report = analyze(conf, policy="bf16")
        assert "DL4J-E301" in report.codes()
        # matching override and explicit fp32 island are both fine
        assert "DL4J-E301" not in analyze(
            self._mlp(dataType="bf16"), policy="bf16").codes()
        assert "DL4J-E301" not in analyze(
            self._mlp(dataType="float32"), policy="bf16").codes()

    def test_e302_large_softmax_axis(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=1024, activation="softmax"))
                .layer(OutputLayer(nOut=3))
                .setInputType(InputType.feedForward(8)).build())
        assert "DL4J-E302" in analyze(conf, policy="bf16").codes()
        # clean: fp32 policy, small axis, or an explicit fp32 island
        assert "DL4J-E302" not in analyze(conf).codes()
        small = (_builder().list()
                 .layer(DenseLayer(nOut=64, activation="softmax"))
                 .layer(OutputLayer(nOut=3))
                 .setInputType(InputType.feedForward(8)).build())
        assert "DL4J-E302" not in analyze(small, policy="bf16").codes()
        island = (_builder().list()
                  .layer(DenseLayer(nOut=1024, activation="softmax",
                                    dataType="float32"))
                  .layer(OutputLayer(nOut=3))
                  .setInputType(InputType.feedForward(8)).build())
        assert "DL4J-E302" not in analyze(island, policy="bf16").codes()

    def test_e302_loss_head_dragged_low(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=16))
                .layer(OutputLayer(nOut=3, dataType="bf16"))
                .setInputType(InputType.feedForward(8)).build())
        assert "DL4J-E302" in analyze(conf, policy="bf16").codes()

    def test_e302_attention_timestep_axis(self):
        from deeplearning4j_tpu.nn.layers import (RnnOutputLayer,
                                                  SelfAttentionLayer)
        def att(t):
            return (_builder().list()
                    .layer(SelfAttentionLayer(nOut=64, nHeads=4,
                                              headSize=16))
                    .layer(RnnOutputLayer(nOut=3, lossFunction="mcxent"))
                    .setInputType(InputType.recurrent(64, t)).build())
        assert "DL4J-E302" in analyze(att(2048), policy="bf16").codes()
        assert "DL4J-E302" not in analyze(att(128), policy="bf16").codes()

    def test_e303_fp16_without_loss_scaling(self):
        conf = self._mlp()
        report = analyze(conf, policy="fp16")
        assert "DL4J-E303" in report.codes()
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        assert "DL4J-E303" not in analyze(
            conf, policy=PrecisionPolicy("float16",
                                         loss_scale=2 ** 15)).codes()

    def test_e303_yolo_overflow_fixture(self):
        """THE acceptance pin: the statically-reconstructed YOLO bug —
        raw [0, 255] input + fp16-class updater state — is E303 at
        validate() time."""
        from deeplearning4j_tpu.nn.layers import LossLayer
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        conf = (_builder(Adam(1e-3)).list()
                .layer(DenseLayer(nOut=32, activation="relu"))
                .layer(LossLayer(lossFunction="mse"))
                .setInputType(InputType.feedForward(16)).build())
        pol = PrecisionPolicy("float16", params="float16",
                              loss_scale=2 ** 15)
        report = conf.validate(policy=pol, data_range="0..255")
        assert "DL4J-E303" in report.codes(), report.format()
        # fp32 updater state holds the ~4e9 second moment: W303 only
        r32 = conf.validate(data_range="0..255")
        assert "DL4J-E303" not in r32.codes()
        assert "DL4J-W303" in r32.codes()
        # normalized input: both clean
        rn = conf.validate(policy=pol, data_range="0..1")
        assert "DL4J-E303" not in rn.codes()
        assert "DL4J-W303" not in rn.codes()

    def test_w301_fp32_sandwich(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=16))
                .layer(DenseLayer(nOut=16, dataType="float32"))
                .layer(DenseLayer(nOut=16))
                .layer(OutputLayer(nOut=3))
                .setInputType(InputType.feedForward(8)).build())
        assert "DL4J-W301" in analyze(conf, policy="bf16").codes()
        # an island at the EDGE (before the fp32 loss head) is not churn
        edge = (_builder().list()
                .layer(DenseLayer(nOut=16))
                .layer(DenseLayer(nOut=16, dataType="float32"))
                .layer(OutputLayer(nOut=3))
                .setInputType(InputType.feedForward(8)).build())
        assert "DL4J-W301" not in analyze(edge, policy="bf16").codes()
        assert "DL4J-W301" not in analyze(conf).codes()


    def test_w301_sequential_only(self):
        """Review regression: W301 reasons about layer adjacency, which
        graph node order is not — the lint stays off for graphs."""
        g = (_graph_builder()
             .addLayer("a", DenseLayer(nOut=16), "in")
             .addLayer("b", DenseLayer(nOut=16, dataType="float32"), "in")
             .addLayer("c", DenseLayer(nOut=16), "in")
             .addLayer("m", DenseLayer(nOut=16), "a", "b")
             .addLayer("out", OutputLayer(nOut=2), "m")
             .setOutputs("out"))
        assert "DL4J-W301" not in analyze(g.build(), policy="bf16",
                                          suppress=["E003"]).codes()

    def test_w302_loss_scale_misconfigurations(self):
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        conf = self._mlp()
        assert "DL4J-W302" in analyze(
            conf, policy=PrecisionPolicy("bfloat16",
                                         loss_scale=1024)).codes()
        assert "DL4J-W302" in analyze(
            conf, policy=PrecisionPolicy("float16",
                                         loss_scale=0.5)).codes()
        assert "DL4J-W302" in analyze(
            conf, policy=PrecisionPolicy("float16",
                                         loss_scale=2.0 ** 30)).codes()
        assert "DL4J-W302" not in analyze(
            conf, policy=PrecisionPolicy("float16",
                                         loss_scale=2 ** 15)).codes()

    def test_w303_unnormalized_input(self):
        conf = self._mlp(updater=Adam(1e-3))
        assert "DL4J-W303" in analyze(conf, data_range="0..255").codes()
        assert "DL4J-W303" not in analyze(
            conf, data_range="0..255,normalized").codes()
        assert "DL4J-W303" not in analyze(conf, data_range="-1..1").codes()
        # a BatchNormalization FIRST does the normalizer's job
        from deeplearning4j_tpu.nn.layers import BatchNormalization
        bn = (_builder(Adam(1e-3)).list()
              .layer(BatchNormalization())
              .layer(DenseLayer(nOut=16, activation="relu"))
              .layer(OutputLayer(nOut=3))
              .setInputType(InputType.feedForward(8)).build())
        assert "DL4J-W303" not in analyze(bn, data_range="0..255").codes()

    def test_data_range_spec_parse_and_coerce(self):
        from deeplearning4j_tpu.analysis.numerics import DataRangeSpec
        r = DataRangeSpec.parse("0..255")
        assert (r.lo, r.hi, r.normalized) == (0.0, 255.0, False)
        assert DataRangeSpec.parse("-1..1,normalized").normalized
        assert DataRangeSpec.coerce((0, 255)).hi == 255
        assert DataRangeSpec.coerce({"lo": 0, "hi": 1}).max_abs == 1.0
        with pytest.raises(ValueError):
            DataRangeSpec.parse("255")
        with pytest.raises(ValueError):
            DataRangeSpec.parse("0..255,bogus")
        with pytest.raises(ValueError):
            DataRangeSpec(5, 1)
        with pytest.raises(TypeError):
            DataRangeSpec.coerce(object())

    def test_policy_resolution_precedence(self):
        """Explicit policy > attached network policy > config dataType."""
        from deeplearning4j_tpu.analysis.numerics import resolve_policy
        conf = self._mlp()
        assert resolve_policy(conf).compute == "float32"
        conf.base.dtype = "bfloat16"
        assert resolve_policy(conf).compute == "bfloat16"
        net = MultiLayerNetwork(self._mlp())
        net.setPrecisionPolicy("bf16")
        assert resolve_policy(net.conf, model=net).compute == "bfloat16"
        assert resolve_policy(net.conf, policy="fp16",
                              model=net).compute == "float16"

    def test_attached_policy_feeds_validate(self):
        net = MultiLayerNetwork((_builder().list()
                                 .layer(DenseLayer(nOut=1024,
                                                   activation="softmax"))
                                 .layer(OutputLayer(nOut=3))
                                 .setInputType(InputType.feedForward(8))
                                 .build()))
        assert "DL4J-E302" not in net.validate().codes()
        net.setPrecisionPolicy("bf16")
        assert "DL4J-E302" in net.validate().codes()

    def test_numerics_codes_documented_and_suppressible(self):
        for code in ("DL4J-E301", "DL4J-E302", "DL4J-E303",
                     "DL4J-W301", "DL4J-W302", "DL4J-W303"):
            assert code in DIAGNOSTIC_CODES
        conf = self._mlp(updater=Adam(1e-3))
        assert "DL4J-W303" not in analyze(conf, data_range="0..255",
                                          suppress=["W303"]).codes()

    def test_graph_config_numerics(self):
        g = (_graph_builder()
             .addLayer("fc", DenseLayer(nOut=16, dataType="float16"), "in")
             .addLayer("out", OutputLayer(nOut=2), "fc")
             .setOutputs("out"))
        assert "DL4J-E301" in analyze(g.build(), policy="bf16").codes()

    def test_zoo_clean_under_default_and_bf16(self):
        """CI gate: every zoo model lints clean for the numerics codes
        under the default fp32 policy AND --policy bf16 — no
        suppressions needed."""
        from deeplearning4j_tpu.models.zoo import ZOO_MODELS
        numerics = ("DL4J-E3", "DL4J-W30")
        for name, cls in ZOO_MODELS.items():
            conf = cls().conf_builder()
            for pol in (None, "bf16"):
                rep = analyze(conf, policy=pol)
                bad = [d for d in rep if d.code.startswith(numerics)]
                assert not bad, (name, pol,
                                 [d.format() for d in bad])

    def test_samediff_numerics_kwargs_run_numerics_lints(self):
        # ISSUE 18 flipped this pin: policy=/data_range= on a recorded
        # graph now run the numerics family instead of raising
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 4))
        w = sd.var("w", np.zeros((4, 2), np.float32))
        x.mmul(w)
        report = analyze(sd, batch_size=8, policy="bf16",
                         data_range="0..255")
        assert "DL4J-W303" in report.codes(), report.format()


class TestNumericsCli:
    def test_cli_policy_flag_zoo_model(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["LeNet", "--policy", "bf16"]) == 0
        assert main(["LeNet", "--policy",
                     "compute=fp16,params=fp32,loss_scale=32768"]) == 0

    def test_cli_fp16_without_scale_fails(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["LeNet", "--policy", "fp16"]) == 1
        assert "DL4J-E303" in capsys.readouterr().out

    def test_cli_bad_policy_and_range_are_usage_errors(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        with pytest.raises(SystemExit) as ei:
            main(["LeNet", "--policy", "float8"])
        assert ei.value.code == 2
        with pytest.raises(SystemExit) as ei:
            main(["LeNet", "--data-range", "255"])
        assert ei.value.code == 2

    def test_cli_data_range_flags_w303(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        import deeplearning4j_tpu.models.zoo as zoo_mod
        # TinyYOLO declares raw pixel input in its docstring; any conv
        # net without a leading BN works for the pin
        rc = main(["TinyYOLO", "--data-range", "0..255"])
        out = capsys.readouterr().out
        assert rc == 1 and "DL4J-W303" in out


class TestPureStaticNumerics:
    def test_numerics_pass_runs_with_jax_blocked(self):
        """analysis/numerics.py imports (and lints duck-typed configs)
        with jax unimportable — the pure-static pin for this pass."""
        code = (
            "import sys, types\n"
            "sys.modules['jax'] = None\n"
            "sys.modules['jax.numpy'] = None\n"
            "from deeplearning4j_tpu.analysis.numerics import (\n"
            "    DataRangeSpec, lint_numerics)\n"
            "from deeplearning4j_tpu.nn.precision import PrecisionPolicy\n"
            "class DenseLayer:\n"
            "    name = 'd'; nIn = 8; nOut = 16; activation = 'relu'\n"
            "    dtype_override = None\n"
            "class LossLayer:\n"
            "    name = 'l'; nIn = 16; nOut = 16; activation = 'identity'\n"
            "    loss_fn = 'mse'; dtype_override = None\n"
            "    def compute_loss(self): pass\n"
            "conf = types.SimpleNamespace(\n"
            "    base=types.SimpleNamespace(updater=None, dtype='float32'),\n"
            "    layers=[DenseLayer(), LossLayer()], input_type=None,\n"
            "    preprocessors={})\n"
            "pol = PrecisionPolicy('float16')\n"
            "diags = lint_numerics(conf, policy=pol,\n"
            "                      data_range=DataRangeSpec(0, 255))\n"
            "codes = [d.code for d in diags]\n"
            "assert 'DL4J-E303' in codes, codes\n"
            "assert 'DL4J-W303' in codes, codes\n"
            "print('PURE-STATIC-NUMERICS-OK')\n")
        proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "PURE-STATIC-NUMERICS-OK" in proc.stdout


# ------------------------------------ module-level concurrency (ISSUE 11)
_MODULE_E201_BAD = """
import threading

RESULTS = []
_counter = 0

def worker():
    global _counter
    for _ in range(100):
        _counter += 1
        RESULTS.append(_counter)

def run():
    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return _counter
"""

_MODULE_E201_CLEAN = """
import threading

RESULTS = []
_counter = 0
_LOCK = threading.Lock()

def worker():
    global _counter
    for _ in range(100):
        with _LOCK:
            _counter += 1
            RESULTS.append(_counter)

def run():
    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    with _LOCK:
        return _counter
"""

_MODULE_CLOSURE_EXEMPT = """
import threading

def run():
    results = []
    def work():
        results.append(1)
    t = threading.Thread(target=work)
    t.start()
    t.join()
    return results
"""

_MODULE_QUEUE_EXEMPT = """
import threading
import queue

TASKS = queue.Queue()

def worker():
    while True:
        item = TASKS.get()
        if item is None:
            return
        TASKS.task_done()

def run():
    t = threading.Thread(target=worker)
    t.start()
    TASKS.put(1)
    TASKS.put(None)
    t.join()
"""


class TestModuleLevelConcurrency:
    """E201/E202 inference over module-level functions sharing globals
    via threading.Thread(target=fn) — the PR-8 carried follow-up."""

    def test_bad_fixture_fires_e201_and_e202(self, tmp_path):
        r = _lint_src(tmp_path, _MODULE_E201_BAD)
        assert "DL4J-E202" in r.codes()       # _counter += 1
        assert "DL4J-E201" in r.codes()       # RESULTS.append(...)
        rmw = [d for d in r if d.code == "DL4J-E202"]
        assert "module global" in rmw[0].message

    def test_clean_bill_when_locked(self, tmp_path):
        r = _lint_src(tmp_path, _MODULE_E201_CLEAN)
        assert not [c for c in r.codes() if c.startswith("DL4J-E20")], \
            r.format()

    def test_local_closure_target_is_exempt(self, tmp_path):
        r = _lint_src(tmp_path, _MODULE_CLOSURE_EXEMPT)
        assert not [c for c in r.codes() if c.startswith("DL4J-E20")], \
            r.format()

    def test_threadsafe_module_primitive_is_exempt(self, tmp_path):
        r = _lint_src(tmp_path, _MODULE_QUEUE_EXEMPT)
        assert not [c for c in r.codes() if c.startswith("DL4J-E20")], \
            r.format()

    def test_reachability_via_plain_calls(self, tmp_path):
        src = _MODULE_E201_BAD.replace(
            "def run():",
            "def entry():\n    worker()\n\ndef run():").replace(
            "Thread(target=worker)", "Thread(target=entry)")
        r = _lint_src(tmp_path, src)
        assert "DL4J-E202" in r.codes()       # worker reached via entry()


    def test_local_shadow_of_module_global_is_exempt(self, tmp_path):
        """Review regression: a function-local that shadows a module
        name (plain assignment makes it local for the whole function)
        is not module state."""
        src = (
            "import threading\n"
            "REGISTRY = {}\n"
            "def worker():\n"
            "    REGISTRY = {}\n"
            "    REGISTRY['k'] = 1\n"
            "    REGISTRY.update(a=2)\n"
            "def run():\n"
            "    t = threading.Thread(target=worker)\n"
            "    t.start(); t.join()\n")
        r = _lint_src(tmp_path, src)
        assert not [c for c in r.codes() if c.startswith("DL4J-E20")], \
            r.format()

    def test_annotated_module_global_is_tracked(self, tmp_path):
        """Review regression: `COUNTS: dict = {}` (AnnAssign) is module
        state like a plain assignment."""
        src = (
            "import threading\n"
            "COUNTS: dict = {}\n"
            "def worker():\n"
            "    COUNTS['k'] = 1\n"
            "def run():\n"
            "    t = threading.Thread(target=worker)\n"
            "    t.start(); t.join()\n"
            "    return COUNTS\n")
        r = _lint_src(tmp_path, src)
        assert "DL4J-E201" in r.codes(), r.format()

    def test_noqa_suppresses_module_findings(self, tmp_path):
        src = _MODULE_E201_BAD.replace(
            "        _counter += 1",
            "        _counter += 1  # dl4j: noqa=E202")
        r = _lint_src(tmp_path, src)
        assert "DL4J-E202" not in r.codes()


# --------------------------------------------- W105 FLOP model (ISSUE 11)
class TestFlopModelExtensions:
    """Attention + conv-LSTM FLOP estimates (the PR-4 carried W105
    follow-up), pinned against a BERT-shaped config analytically."""

    def test_attention_flops_match_analytic_bert_block(self):
        from deeplearning4j_tpu.analysis.distribution import (
            _approx_flops, _propagate_types)
        from deeplearning4j_tpu.nn.layers import (RnnOutputLayer,
                                                  SelfAttentionLayer)
        T, H, HEADS, HS = 128, 768, 12, 64
        conf = (_builder().list()
                .layer(SelfAttentionLayer(nOut=H, nHeads=HEADS,
                                          headSize=HS))
                .layer(RnnOutputLayer(nOut=2, lossFunction="mcxent"))
                .setInputType(InputType.recurrent(H, T)).build())
        types = _propagate_types(conf)
        got = _approx_flops(conf.layers[0], types[0][0], types[0][1])
        E = HEADS * HS
        proj = 2 * (3 * H * E + E * H) * T     # Wq/Wk/Wv + Wo, per step
        attn = 2 * 2 * T * T * E               # QK^T + attn@V
        assert got == proj + attn, (got, proj + attn)
        # the attention term is the part the old model undercounted
        assert attn / (proj + attn) > 0.05

    def test_conv_lstm_param_shapes_match_initialize(self):
        from deeplearning4j_tpu.nn.layers import ConvLSTM2D
        layer = ConvLSTM2D(nOut=32, kernelSize=(3, 3))
        layer.nIn = 16
        shapes = layer.param_shapes()
        assert shapes == {"W": (128, 16, 3, 3), "RW": (128, 32, 3, 3),
                          "b": (128,)}

    def test_unknown_timesteps_degrade_to_zero_attention_term(self):
        from deeplearning4j_tpu.analysis.distribution import _attention_flops
        from deeplearning4j_tpu.nn.layers import SelfAttentionLayer
        layer = SelfAttentionLayer(nOut=64, nHeads=4, headSize=16)
        layer.nIn = 64
        assert _attention_flops(layer, InputType.recurrent(64, -1)) == 0
        assert _attention_flops(layer, None) == 0

    def test_w105_counts_attention_stage(self):
        """A transformer stage opposite a tiny dense stage now trips the
        imbalance lint — before the attention term it read as nearly
        empty."""
        from deeplearning4j_tpu.nn.layers import (RnnOutputLayer,
                                                  SelfAttentionLayer)
        conf = (_builder().list()
                .layer(SelfAttentionLayer(nOut=512, nHeads=8, headSize=64))
                .layer(SelfAttentionLayer(nOut=512, nHeads=8, headSize=64))
                .layer(SelfAttentionLayer(nOut=512, nHeads=8, headSize=64))
                .layer(RnnOutputLayer(nOut=2, lossFunction="mcxent"))
                .setInputType(InputType.recurrent(512, 256)).build())
        report = analyze(conf, mesh={"data": 2, "pipe": 2},
                         pipeline=2)
        assert "DL4J-W105" in report.codes(), report.format()

    def test_e303_scaled_gradient_overflow(self):
        """Review regression: the compute-overflow clause tests the
        LOSS-SCALED gradient estimate — a scale big enough to push raw
        [0,255] gradients past fp16-max is flagged even with Sgd (no
        squaring state), and a modest scale on normalized input is
        not."""
        from deeplearning4j_tpu.nn.layers import LossLayer
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        conf = (_builder().list()
                .layer(DenseLayer(nOut=32, activation="relu"))
                .layer(LossLayer(lossFunction="mse"))
                .setInputType(InputType.feedForward(16)).build())
        pol = PrecisionPolicy("float16", loss_scale=2 ** 15)
        assert "DL4J-E303" in analyze(conf, policy=pol,
                                      data_range="0..255").codes()
        assert "DL4J-E303" not in analyze(conf, policy=pol,
                                          data_range="0..1").codes()

    def test_parameter_shadow_is_exempt(self, tmp_path):
        """Review regression: a parameter shadowing a module name binds
        locally — mutating the argument is not a module-global write."""
        src = (
            "import threading\n"
            "RESULTS = []\n"
            "def worker(RESULTS):\n"
            "    RESULTS.append(1)\n"
            "def run():\n"
            "    t = threading.Thread(target=worker, args=([],))\n"
            "    t.start(); t.join()\n")
        r = _lint_src(tmp_path, src)
        assert not [c for c in r.codes() if c.startswith("DL4J-E20")], \
            r.format()


# --------------------------------------------- ISSUE 18: import lints
class TestGraphVertexPropagation:
    """Satellite: per-vertex sharding/type propagation — graph configs
    get the same W105/W106 pipeline findings multilayer configs do."""

    def test_w105_fires_on_graph_pipeline_imbalance(self):
        conf = (_graph_builder()
                .setInputTypes(InputType.feedForward(64))
                .addLayer("a", DenseLayer(nOut=4096), "in")
                .addLayer("b", DenseLayer(nOut=4096), "a")
                .addLayer("c", DenseLayer(nOut=16), "b")
                .addLayer("out", OutputLayer(nOut=4), "c")
                .setOutputs("out").build())
        report = analyze(conf, batch_size=32, mesh="data=2,pipe=2",
                         pipeline=2)
        assert "DL4J-W105" in report.codes(), report.format()

    def test_types_propagate_through_merge_vertex(self):
        from deeplearning4j_tpu.analysis.distribution import \
            _propagate_graph_types
        conf = (_graph_builder()
                .addLayer("a", DenseLayer(nOut=32), "in")
                .addLayer("b", DenseLayer(nOut=32), "in")
                .addVertex("m", MergeVertex(), "a", "b")
                .addLayer("c", DenseLayer(nOut=16), "m")
                .addLayer("out", OutputLayer(nOut=4), "c")
                .setOutputs("out").build())
        types = _propagate_graph_types(conf)
        in_t, out_t = types["c"]
        assert in_t.size == 64          # 32 + 32 through the MergeVertex
        assert out_t.size == 16
        # and the linted graph stays clean under a plain data mesh
        assert analyze(conf, batch_size=32, mesh={"data": 2}).ok()

    def test_balanced_graph_pipeline_clean(self):
        conf = (_graph_builder()
                .setInputTypes(InputType.feedForward(64))
                .addLayer("a", DenseLayer(nOut=256), "in")
                .addLayer("b", DenseLayer(nOut=256), "a")
                .addLayer("c", DenseLayer(nOut=256), "b")
                .addLayer("out", OutputLayer(nOut=256), "c")
                .setOutputs("out").build())
        report = analyze(conf, batch_size=32, mesh="data=2,pipe=2",
                         pipeline=2)
        assert "DL4J-W105" not in report.codes(), report.format()


class TestPureStaticImports:
    """The graph IR and the import lints run with jax BLOCKED — both
    operate on declared shapes and numpy arrays only (ISSUE 18
    acceptance)."""

    def test_graphir_and_imports_run_with_jax_blocked(self):
        code = (
            "import sys\n"
            "sys.modules['jax'] = None\n"
            "sys.modules['jax.numpy'] = None\n"
            "import numpy as np\n"
            "from types import SimpleNamespace as NS\n"
            "from deeplearning4j_tpu.analysis import MeshSpec\n"
            "from deeplearning4j_tpu.analysis import graphir, "
            "imports as imp\n"
            "class Arr:\n"
            "    def __init__(self, shape, dtype='float32'):\n"
            "        self.shape, self.dtype = shape, dtype\n"
            "class Node:\n"
            "    def __init__(self, op, ins, outs):\n"
            "        self.op, self.inputs, self.outputs = op, ins, outs\n"
            "        self.attrs = {}\n"
            "sd = NS(_nodes=[Node('matmul', ['x', 'w'], ['y'])],\n"
            "        _placeholders={'x': ((None, 4096), 'float32')},\n"
            "        _constants={},\n"
            "        _variables={'w': Arr((4096, 260))},\n"
            "        _loss_variables=[], training_config=None)\n"
            "ir = graphir.from_samediff(sd, batch_size=12)\n"
            "lay = {d.code for d in graphir.lint_ir_layout(ir, 12, 8)}\n"
            "assert 'DL4J-W101' in lay, lay\n"
            "mesh = MeshSpec({'data': 8})\n"
            "dist = {d.code for d in\n"
            "        graphir.lint_ir_distribution(ir, mesh, 12)}\n"
            "assert 'DL4J-E101' in dist, dist\n"
            "num = {d.code for d in graphir.lint_ir_numerics(\n"
            "    ir, policy='bf16', data_range='0..255')}\n"
            "assert 'DL4J-W303' in num, num\n"
            "assert imp.lint_placeholder_shape((None, None, 3), 'x')\n"
            "assert imp.lint_narrowed_array(\n"
            "    np.eye(2, dtype=np.float64), 'w')\n"
            "assert imp.fold_overflow_diags(\n"
            "    'Add', 's', [np.asarray([np.inf], np.float32)])\n"
            "print('PURE-STATIC-IMPORTS-OK')\n")
        proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "PURE-STATIC-IMPORTS-OK" in proc.stdout


class TestGraphIRParity:
    """from_multilayer is the parity proof: lowering a NATIVE config to
    the IR and linting the IR yields the same distribution codes the
    native pass emits."""

    DIST = {"DL4J-E101", "DL4J-E102", "DL4J-E103", "DL4J-E104",
            "DL4J-W104", "DL4J-W105", "DL4J-W106", "DL4J-W107"}

    def test_from_multilayer_distribution_parity(self):
        from deeplearning4j_tpu.analysis import graphir
        conf = _wide_mlp()
        mesh = MeshSpec({"data": 8, "model": 2}, hbm_gb=0.05)
        native = {d.code
                  for d in analyze(conf, batch_size=6, mesh=mesh)} & self.DIST
        ir = graphir.from_multilayer(conf, batch_size=6)
        lowered = {d.code for d in graphir.lint_ir_distribution(
            ir, mesh, 6)} & self.DIST
        assert native == lowered, (native, lowered)
        assert "DL4J-E101" in lowered    # the set is non-trivial

    def test_onnx_dtype_names_pinned_to_proto(self):
        from deeplearning4j_tpu.analysis import graphir
        from deeplearning4j_tpu.modelimport import onnx_proto as P
        want = {P.DT_FLOAT: "float32", P.DT_UINT8: "uint8",
                P.DT_INT8: "int8", P.DT_UINT16: "uint16",
                P.DT_INT16: "int16", P.DT_INT32: "int32",
                P.DT_INT64: "int64", P.DT_BOOL: "bool",
                P.DT_FLOAT16: "float16", P.DT_DOUBLE: "float64",
                P.DT_UINT32: "uint32", P.DT_UINT64: "uint64",
                P.DT_BFLOAT16: "bfloat16"}
        assert graphir.ONNX_DTYPE_NAMES == want


class TestImportReportMerge:
    """analyze() folds an attached import_report into the validation
    report — import-time findings surface at validate() time."""

    def test_import_report_diags_surface_in_analyze(self):
        from deeplearning4j_tpu.analysis import ValidationReport
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 4))
        w = sd.var("w", np.ones((4, 2), np.float32))
        (x.mmul(w)).rename("y")
        sd.import_report = ValidationReport(
            [Diagnostic("DL4J-W161", Severity.WARNING, "input 'x'",
                        "seeded import finding")], subject="import")
        report = analyze(sd, batch_size=8)
        assert "DL4J-W161" in report.codes(), report.format()
        # suppress= reaches merged import findings too
        quiet = analyze(sd, batch_size=8, suppress=["W161"])
        assert "DL4J-W161" not in quiet.codes()


class TestImportsSelfLint:
    """The imported-fixture gate (tools/lint.py run_imports): the shipped
    TF conformance corpus lints clean with ZERO suppressions."""

    def _lint_mod(self):
        spec = importlib.util.spec_from_file_location(
            "repo_lint", REPO / "tools" / "lint.py")
        lint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lint)
        return lint

    def test_fixture_corpus_lints_clean(self, capsys):
        lint = self._lint_mod()
        assert lint._pyproject_imports_suppress() == [], \
            "the corpus must stay clean with zero suppressions"
        rc = lint.run_imports()
        out = capsys.readouterr().out
        assert rc == 0, f"imported-fixture gate found issues:\n{out}"

    def test_missing_corpus_skips_clean(self, tmp_path, capsys):
        lint = self._lint_mod()
        assert lint.run_imports(tmp_path / "nope") == 0
        assert "skipped" in capsys.readouterr().out

    def test_pyproject_imports_suppress_parse(self, tmp_path):
        lint = self._lint_mod()
        (tmp_path / "pyproject.toml").write_text(
            "[tool.dl4j.imports]\n"
            'suppress = ["W161"]\n'
            "[tool.other]\n"
            'suppress = ["W999"]\n')
        old = lint.REPO
        try:
            lint.REPO = tmp_path
            assert lint._pyproject_imports_suppress() == ["W161"]
            assert lint._pyproject_concurrency_suppress() == []
        finally:
            lint.REPO = old


class TestCliSameDiff:
    def test_samediff_flag_lints_recorded_graph(self, tmp_path,
                                                monkeypatch, capsys):
        mod = tmp_path / "sdmodel.py"
        mod.write_text(
            "import numpy as np\n"
            "from deeplearning4j_tpu.autodiff.samediff import SameDiff\n"
            "sd = SameDiff.create()\n"
            "x = sd.placeHolder('x', shape=(None, 4))\n"
            "w = sd.var('w', np.ones((4, 2), np.float32))\n"
            "y = x.mmul(w)\n")
        monkeypatch.syspath_prepend(str(tmp_path))
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["--samediff", "sdmodel:sd"]) == 0
        assert "clean" in capsys.readouterr().out
