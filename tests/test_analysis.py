"""Static analyzer (ISSUE 3): one seeded misconfiguration per diagnostic
code, clean-bill assertions over the whole model zoo + fixtures, the
recompile-churn detector, strict init, did-you-mean kwarg rejection, the
EarlyStoppingTrainer megastep path, the CLI, and the repo lint gate."""

import ast
import importlib.util
import pathlib
import subprocess
import sys
import warnings

import numpy as np
import pytest

import deeplearning4j_tpu.analysis as analysis
from deeplearning4j_tpu.analysis import (DIAGNOSTIC_CODES, Diagnostic,
                                         ModelValidationError,
                                         RecompileChurnDetector, Severity,
                                         analyze, get_churn_detector)
from deeplearning4j_tpu.data.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.config import (InputType, MultiLayerConfiguration,
                                          NeuralNetConfiguration)
from deeplearning4j_tpu.nn.graph import (ComputationGraph, ElementWiseVertex,
                                         MergeVertex)
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer, LSTM,
                                          OutputLayer, RnnOutputLayer,
                                          SubsamplingLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.updaters import Adam, Sgd

REPO = pathlib.Path(__file__).resolve().parent.parent


def _builder(updater=None):
    return (NeuralNetConfiguration.Builder()
            .seed(7).updater(updater or Sgd(0.1)).weightInit("xavier"))


def _mlp_conf(n_in=4, hidden=8, n_out=2, updater=None):
    return (_builder(updater).list()
            .layer(DenseLayer(nOut=hidden, activation="relu"))
            .layer(OutputLayer(nOut=n_out, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(n_in))
            .build())


def _graph_builder():
    return (_builder().graphBuilder()
            .addInputs("in")
            .setInputTypes(InputType.feedForward(4)))


def _one_hot(n, k=2, seed=0):
    rng = np.random.RandomState(seed)
    y = np.zeros((n, k), np.float32)
    y[np.arange(n), rng.randint(0, k, n)] = 1.0
    return y


class TestSeededDiagnostics:
    """Each documented code fires on its seeded misconfiguration."""

    def test_e001_nin_mismatch(self):
        conf = (_builder().list()
                .layer(DenseLayer(nIn=300, nOut=16))
                .layer(OutputLayer(nOut=4))
                .setInputType(InputType.feedForward(128))
                .build())
        report = conf.validate()
        assert "DL4J-E001" in report.codes()
        assert not report.ok()

    def test_e001_unresolvable_nin(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=16))
                .layer(OutputLayer(nOut=4, nIn=16))
                .build())     # no setInputType -> nIn can't be inferred
        assert "DL4J-E001" in conf.validate().codes()

    def test_e002_cycle(self):
        g = (_graph_builder()
             .addLayer("a", DenseLayer(nIn=4, nOut=4), "b")
             .addLayer("b", DenseLayer(nIn=4, nOut=4), "a")
             .addLayer("out", OutputLayer(nIn=4, nOut=2), "b")
             .setOutputs("out"))
        report = g.validate()      # build() would raise; validate reports
        assert "DL4J-E002" in report.codes()

    def test_e003_undefined_input(self):
        g = (_graph_builder()
             .addLayer("out", OutputLayer(nIn=4, nOut=2), "nonexistent")
             .setOutputs("out"))
        report = g.validate()
        assert "DL4J-E003" in report.codes()
        assert report.errors()

    def test_e003_dangling_vertex(self):
        g = (_graph_builder()
             .addLayer("used", DenseLayer(nOut=4), "in")
             .addLayer("orphan", DenseLayer(nOut=4), "in")
             .addLayer("out", OutputLayer(nOut=2), "used")
             .setOutputs("out"))
        report = analyze(g.build())
        dangling = [d for d in report if d.code == "DL4J-E003"]
        assert dangling and dangling[0].severity is Severity.WARNING
        assert "orphan" in dangling[0].location

    def test_e004_duplicate_graph_name(self):
        g = (_graph_builder()
             .addLayer("fc", DenseLayer(nOut=4), "in")
             .addLayer("fc", DenseLayer(nOut=4), "in")
             .addLayer("out", OutputLayer(nOut=2), "fc")
             .setOutputs("out"))
        assert "DL4J-E004" in g.validate().codes()

    def test_e004_duplicate_explicit_layer_name(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=8, name="fc"))
                .layer(DenseLayer(nOut=8, name="fc"))
                .layer(OutputLayer(nOut=2))
                .setInputType(InputType.feedForward(4))
                .build())
        assert "DL4J-E004" in conf.validate().codes()

    def test_e005_missing_cnn_to_dense_flatten(self):
        conf = (_builder().list()
                .layer(ConvolutionLayer(nIn=1, nOut=8, kernelSize=(3, 3)))
                .layer(DenseLayer(nIn=800, nOut=10))
                .layer(OutputLayer(nIn=10, nOut=2))
                .build())     # no input type -> no auto preprocessor
        assert "DL4J-E005" in conf.validate().codes()

    def test_e006_elementwise_shape_conflict(self):
        g = (_builder().graphBuilder()
             .addInputs("in")
             .setInputTypes(InputType.convolutional(8, 8, 3))
             .addLayer("a", ConvolutionLayer(nOut=4, kernelSize=(1, 1)), "in")
             .addLayer("b", ConvolutionLayer(nOut=8, kernelSize=(1, 1)), "in")
             .addVertex("add", ElementWiseVertex("Add"), "a", "b")
             .addLayer("out", OutputLayer(nOut=2), "add")
             .setOutputs("out"))
        assert "DL4J-E006" in analyze(g.build()).codes()

    def test_e006_merge_spatial_conflict(self):
        g = (_builder().graphBuilder()
             .addInputs("in")
             .setInputTypes(InputType.convolutional(8, 8, 3))
             .addLayer("a", ConvolutionLayer(nOut=4, kernelSize=(1, 1)), "in")
             .addLayer("b", ConvolutionLayer(nOut=4, kernelSize=(1, 1),
                                             stride=(2, 2)), "in")
             .addVertex("cat", MergeVertex(), "a", "b")
             .addLayer("out", OutputLayer(nOut=2), "cat")
             .setOutputs("out"))
        assert "DL4J-E006" in analyze(g.build()).codes()

    def test_e007_shape_inference_failure(self):
        lb = (_builder().list()
              .layer(DenseLayer())          # nOut missing
              .layer(OutputLayer(nOut=2))
              .setInputType(InputType.feedForward(4)))
        assert "DL4J-E007" in analyze(lb).codes()   # unbuilt builder

    def test_e008_missing_loss_head(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=8))
                .layer(DenseLayer(nOut=2))
                .setInputType(InputType.feedForward(4))
                .build())
        assert "DL4J-E008" in conf.validate().codes()

    def test_w001_softmax_mse(self):
        conf = (_builder().list()
                .layer(OutputLayer(nOut=4, lossFunction="mse",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(4))
                .build())
        report = conf.validate()
        assert "DL4J-W001" in report.codes()
        assert report.ok()                  # warning, not error
        assert not report.ok(warnings_as_errors=True)

    def test_w001_sigmoid_multiclass(self):
        conf = (_builder().list()
                .layer(OutputLayer(nOut=4, lossFunction="mcxent",
                                   activation="sigmoid"))
                .setInputType(InputType.feedForward(4))
                .build())
        assert "DL4J-W001" in conf.validate().codes()

    def test_w002_tbptt_without_recurrence(self):
        conf = (_builder().list()
                .layer(DenseLayer(nOut=8))
                .layer(OutputLayer(nOut=2))
                .setInputType(InputType.feedForward(4))
                .backpropType("tbptt", 16)
                .build())
        assert "DL4J-W002" in conf.validate().codes()

    def test_w002_absent_on_recurrent_net(self):
        conf = (_builder().list()
                .layer(LSTM(nOut=8))
                .layer(RnnOutputLayer(nOut=2))
                .setInputType(InputType.recurrent(4, 10))
                .backpropType("tbptt", 16)
                .build())
        assert "DL4J-W002" not in conf.validate().codes()

    def test_w003_frozen_with_stateful_updater(self):
        net = MultiLayerNetwork(_mlp_conf(updater=Adam(1e-3)))
        net._frozen_layers = {0}
        report = net.validate()
        assert "DL4J-W003" in report.codes()
        # Sgd is stateless -> no warning
        net2 = MultiLayerNetwork(_mlp_conf(updater=Sgd(0.1)))
        net2._frozen_layers = {0}
        assert "DL4J-W003" not in net2.validate().codes()

    def test_w101_mxu_padding_waste(self):
        conf = _mlp_conf(hidden=300)        # 300 -> 384 lanes, 22% dead
        report = conf.validate()
        w101 = [d for d in report if d.code == "DL4J-W101"]
        assert w101 and "384" in w101[0].message
        assert "DL4J-W101" not in _mlp_conf(hidden=512).validate().codes()

    def test_w102_non_native_dtype(self):
        conf = (_builder().dataType("float64").list()
                .layer(OutputLayer(nOut=2))
                .setInputType(InputType.feedForward(4))
                .build())
        assert "DL4J-W102" in conf.validate().codes()

    def test_w103_batch_mesh_divisibility(self):
        conf = _mlp_conf()
        assert "DL4J-W103" in conf.validate(batch_size=6,
                                            data_devices=4).codes()
        assert "DL4J-W103" not in conf.validate(batch_size=8,
                                                data_devices=4).codes()


class TestChurnDetector:
    def test_w201_fires_past_threshold(self):
        from deeplearning4j_tpu.profiler.metrics import MetricsRegistry
        reg = MetricsRegistry()
        det = RecompileChurnDetector(threshold=3, registry=reg)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            results = [det.record("test.site", (("shape", i),))
                       for i in range(5)]
        assert results[:3] == [None, None, None]
        assert isinstance(results[3], Diagnostic)       # 4th distinct > 3
        assert results[3].code == "DL4J-W201"
        assert results[4] is None                       # flagged once
        assert any("DL4J-W201" in str(w.message) for w in caught)
        # repeats are free
        assert det.record("test.site", (("shape", 0),)) is None
        assert det.signature_count("test.site") == 5
        child = reg.get("dl4j_recompiles_total").children()[("test.site",)]
        assert child.value == 5
        assert [d.code for d in det.diagnostics_for(None)] == ["DL4J-W201"]
        det.reset()
        assert det.signature_count("test.site") == 0

    def test_fingerprint_shape_dtype_sensitivity(self):
        a = np.zeros((4, 3), np.float32)
        b = np.zeros((5, 3), np.float32)
        c = np.zeros((4, 3), np.float64)
        from deeplearning4j_tpu.analysis import array_fingerprint
        assert array_fingerprint(a) != array_fingerprint(b)
        assert array_fingerprint(a) != array_fingerprint(c)
        assert array_fingerprint(a, None) == array_fingerprint(a, None)

    def test_model_fit_churn_surfaces_in_validate(self):
        det = get_churn_detector()
        old_threshold = det.threshold
        det.threshold = 3
        try:
            net = MultiLayerNetwork(_mlp_conf()).init()
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                for n in (1, 2, 3, 4, 5):   # 5 distinct batch shapes
                    net.fit(DataSet(np.random.RandomState(n)
                                    .rand(n, 4).astype(np.float32),
                                    _one_hot(n)))
            report = net.validate()
            assert "DL4J-W201" in report.codes()
            # a fresh model has no churn findings
            fresh = MultiLayerNetwork(_mlp_conf())
            assert "DL4J-W201" not in fresh.validate().codes()
        finally:
            det.threshold = old_threshold


class TestEntryPoints:
    def test_strict_init_raises_on_errors(self):
        conf = (_builder().list()
                .layer(DenseLayer(nIn=300, nOut=16))
                .layer(OutputLayer(nOut=4))
                .setInputType(InputType.feedForward(128))
                .build())
        net = MultiLayerNetwork(conf)
        with pytest.raises(ModelValidationError) as ei:
            net.init(strict=True)
        assert "DL4J-E001" in str(ei.value)
        net.init()                          # non-strict path unchanged
        assert net._initialized

    def test_strict_init_graph(self):
        g = (_graph_builder()
             .addLayer("fc", DenseLayer(nOut=8), "in")
             .addLayer("out", DenseLayer(nOut=2), "fc")   # not a loss head
             .setOutputs("out"))
        net = ComputationGraph(g.build())
        with pytest.raises(ModelValidationError):
            net.init(strict=True)

    def test_strict_init_passes_clean_model(self):
        net = MultiLayerNetwork(_mlp_conf())
        net.init(strict=True)
        assert net._initialized

    def test_validate_runs_no_jax_trace(self):
        # validate() on an uninitialized net must not allocate params
        net = MultiLayerNetwork(_mlp_conf())
        net.validate()
        assert not net._initialized

    def test_tbptt_config_roundtrip(self):
        conf = (_builder().list()
                .layer(LSTM(nOut=8))
                .layer(RnnOutputLayer(nOut=2))
                .setInputType(InputType.recurrent(4, 10))
                .backpropType("tbptt", 16)
                .build())
        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert back.backprop_type == "tbptt"
        assert back.tbptt_length == 16


class TestDidYouMean:
    def test_layer_kwarg_typo(self):
        with pytest.raises(TypeError, match=r"did you mean 'nOut'"):
            DenseLayer(nOutt=8)

    def test_layer_kwarg_unknown(self):
        with pytest.raises(TypeError, match="unknown config key"):
            ConvolutionLayer(nOut=8, zebra=1)

    def test_subclass_kwargs_still_accepted(self):
        layer = ConvolutionLayer(nOut=8, kernelSize=(3, 3),
                                 convolutionMode="same", hasBias=False)
        assert layer.mode == "same" and not layer.has_bias

    def test_builder_method_typo(self):
        with pytest.raises(AttributeError, match="did you mean 'updater'"):
            NeuralNetConfiguration.Builder().updatr(Sgd(0.1))

    def test_list_builder_method_typo(self):
        with pytest.raises(AttributeError, match="setInputType"):
            _builder().list().setInputTyp(InputType.feedForward(4))


class TestZooCleanBill:
    def test_every_zoo_model_is_clean(self):
        from deeplearning4j_tpu.models.zoo import all_zoo_models
        for name, net in all_zoo_models():
            report = analyze(net)
            assert report.ok(warnings_as_errors=True), \
                f"{name} not clean:\n{report.format()}"

    def test_fixture_configs_are_clean(self):
        fixtures = [
            _mlp_conf(),
            (_builder().list()
             .layer(ConvolutionLayer(nOut=8, kernelSize=(3, 3)))
             .layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
             .layer(DenseLayer(nOut=16, activation="relu"))
             .layer(OutputLayer(nOut=2))
             .setInputType(InputType.convolutional(12, 12, 1))
             .build()),
            (_builder().list()
             .layer(LSTM(nOut=8))
             .layer(RnnOutputLayer(nOut=3))
             .setInputType(InputType.recurrent(5, 7))
             .build()),
        ]
        for conf in fixtures:
            report = conf.validate()
            assert report.ok(warnings_as_errors=True), report.format()

    def test_documented_code_table_is_complete(self):
        assert len(DIAGNOSTIC_CODES) >= 10
        for code in DIAGNOSTIC_CODES:
            assert code.startswith("DL4J-")
        with pytest.raises(ValueError):
            Diagnostic("DL4J-E999", Severity.ERROR, "x", "undocumented")


class TestPureStatic:
    """The analyzer is jax-free: no module-scope jax imports (AST check)
    and the package imports with jax blocked (subprocess check)."""

    @staticmethod
    def _module_scope_imports(tree):
        out = []

        def visit(stmts):
            for node in stmts:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue          # lazy imports are fine
                if isinstance(node, ast.Import):
                    out.extend(a.name for a in node.names)
                elif isinstance(node, ast.ImportFrom):
                    out.append(node.module or "")
                for field in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(node, field, None)
                    if sub:
                        visit([s for s in sub if isinstance(s, ast.stmt)])
        visit(tree.body)
        return out

    def test_no_module_scope_jax_imports(self):
        pkg = pathlib.Path(analysis.__file__).parent
        for py in sorted(pkg.glob("*.py")):
            tree = ast.parse(py.read_text(encoding="utf-8"))
            for mod in self._module_scope_imports(tree):
                root = mod.split(".")[0]
                assert root not in ("jax", "jaxlib"), \
                    f"{py.name} imports {mod} at module scope"

    def test_analysis_package_imports_with_jax_blocked(self):
        code = (
            "import sys\n"
            "sys.modules['jax'] = None\n"           # ImportError on import
            "sys.modules['jax.numpy'] = None\n"
            "import deeplearning4j_tpu.analysis as a\n"
            "r = a.ValidationReport(subject='x')\n"
            "a.get_churn_detector().record('s', ((1,), 'f32', False))\n"
            "d = a.Diagnostic('DL4J-E001', a.Severity.ERROR, 'l', 'm')\n"
            "print('PURE-STATIC-OK')\n")
        proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "PURE-STATIC-OK" in proc.stdout


class TestEarlyStoppingMegasteps:
    def _train(self, steps_per_dispatch):
        from deeplearning4j_tpu.train.earlystopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            EarlyStoppingTrainer, MaxEpochsTerminationCondition)
        rng = np.random.RandomState(0)
        train = DataSet(rng.rand(32, 4).astype(np.float32), _one_hot(32))
        val = DataSet(rng.rand(16, 4).astype(np.float32), _one_hot(16, seed=1))
        net = MultiLayerNetwork(_mlp_conf()).init(seed=99)
        cfg = EarlyStoppingConfiguration.Builder() \
            .scoreCalculator(DataSetLossCalculator(
                ListDataSetIterator(val, 8))) \
            .epochTerminationConditions(MaxEpochsTerminationCondition(2)) \
            .build()
        trainer = EarlyStoppingTrainer(
            cfg, net, ListDataSetIterator(train, 8),
            steps_per_dispatch=steps_per_dispatch)
        result = trainer.fit()
        return net, result

    def test_k_step_path_matches_single_step(self):
        net1, res1 = self._train(1)
        net2, res2 = self._train(2)
        assert res1.total_epochs == res2.total_epochs == 2
        assert net1._iteration == net2._iteration == 8   # 4 batches x 2
        np.testing.assert_allclose(np.asarray(net1.params()),
                                   np.asarray(net2.params()),
                                   rtol=0, atol=0)       # bit-exact
        assert res2.best_score == pytest.approx(res1.best_score)

    def test_iteration_condition_checked_between_dispatches(self):
        from deeplearning4j_tpu.train.earlystopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            EarlyStoppingTrainer, MaxEpochsTerminationCondition,
            MaxScoreIterationTerminationCondition)
        rng = np.random.RandomState(0)
        train = DataSet(rng.rand(32, 4).astype(np.float32), _one_hot(32))
        net = MultiLayerNetwork(_mlp_conf()).init(seed=99)
        cfg = EarlyStoppingConfiguration.Builder() \
            .scoreCalculator(DataSetLossCalculator(
                ListDataSetIterator(train, 8))) \
            .epochTerminationConditions(MaxEpochsTerminationCondition(3)) \
            .iterationTerminationConditions(
                MaxScoreIterationTerminationCondition(-1.0)) \
            .build()
        result = EarlyStoppingTrainer(cfg, net,
                                      ListDataSetIterator(train, 8),
                                      steps_per_dispatch=2).fit()
        assert result.termination_reason == "IterationTerminationCondition"
        assert net._iteration == 2      # one 2-step dispatch, then stop


class TestCli:
    def test_zoo_lint_exits_zero(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["--zoo"]) == 0
        out = capsys.readouterr().out
        assert "16 model(s) linted: 16 clean" in out

    def test_single_model_by_name(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["LeNet"]) == 0
        assert "LeNet: clean" in capsys.readouterr().out

    def test_findings_fail_the_exit_code(self, capsys, tmp_path,
                                         monkeypatch):
        mod = tmp_path / "badmodel.py"
        mod.write_text(
            "from deeplearning4j_tpu.nn.config import (InputType,\n"
            "    NeuralNetConfiguration)\n"
            "from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer\n"
            "conf = (NeuralNetConfiguration.Builder().list()\n"
            "        .layer(DenseLayer(nIn=300, nOut=16))\n"
            "        .layer(OutputLayer(nOut=4))\n"
            "        .setInputType(InputType.feedForward(128))\n"
            "        .build())\n")
        monkeypatch.syspath_prepend(str(tmp_path))
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["badmodel:conf"]) == 1
        assert "DL4J-E001" in capsys.readouterr().out


class TestRepoLintGate:
    def test_repo_lints_clean(self, capsys):
        spec = importlib.util.spec_from_file_location(
            "repo_lint", REPO / "tools" / "lint.py")
        lint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lint)
        rc = lint.run_fallback(lint.DEFAULT_PATHS)
        out = capsys.readouterr().out
        assert rc == 0, f"repo lint found issues:\n{out}"
