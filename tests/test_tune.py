"""ISSUE 17 coverage: the ``tune/`` autotuner — search space, persistent
records, the driver's search phases, the loss-parity gate, the
auto-apply wiring (``fit(tune="auto")`` / ``warmup(tuned=True)`` /
registry load), the proactive conv-stack lint, and the CLI acceptance
path (tune in one process, zero-compile apply in a fresh one)."""

import json
import os
import subprocess
import sys
import warnings
from types import SimpleNamespace
from unittest import mock

import numpy as np
import pytest

from deeplearning4j_tpu.analysis import churn as _churn
from deeplearning4j_tpu.analysis import layout as _layout
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn import compilecache as cc
from deeplearning4j_tpu.nn.config import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                          OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import stepping
from deeplearning4j_tpu.tune import driver as tdriver
from deeplearning4j_tpu.tune import records as trecords
from deeplearning4j_tpu.tune.space import (AXES, TuningPlan, TuningSpace,
                                           axis_priority)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def store(tmp_path):
    """A per-test tuning-record directory, warned-set cleared."""
    trecords.configure(str(tmp_path))
    trecords.reset_warned()
    yield str(tmp_path)
    trecords.reset_configuration()
    trecords.reset_warned()


def tiny_net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed).weightInit("relu")
            .list()
            .layer(ConvolutionLayer(kernelSize=(3, 3), padding=(1, 1),
                                    nOut=8, activation="relu"))
            .layer(SubsamplingLayer(poolingType="max", kernelSize=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(nOut=16, activation="relu"))
            .layer(OutputLayer(nOut=4, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.convolutional(8, 8, 3))
            .build())
    return MultiLayerNetwork(conf).init()


def tiny_data(n=4):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 3, 8, 8).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, n)]
    return x, y


# ------------------------------------------------------------- the space
class TestTuningSpace:

    def test_for_model_enumeration_deterministic(self):
        space = TuningSpace.for_model(max_steps_per_dispatch=16)
        assert space.size == 96
        a = [p.signature() for p in space.enumerate_plans()]
        b = [p.signature() for p in space.enumerate_plans()]
        assert a == b
        assert len(set(a)) == 96          # every signature is unique

    def test_sample_deterministic_across_seeds(self):
        space = TuningSpace.for_model(max_steps_per_dispatch=16)
        s1 = [p.signature() for p in space.sample(10, seed=3)]
        s2 = [p.signature() for p in space.sample(10, seed=3)]
        s3 = [p.signature() for p in space.sample(10, seed=4)]
        assert s1 == s2
        assert s1 != s3
        assert len(set(s1)) == 10

    def test_plan_config_roundtrip_and_replace(self):
        plan = TuningPlan(compute_layout="NHWC", fuse_epilogues=True,
                          steps_per_dispatch=4, precision="bf16",
                          prefetch=0)
        back = TuningPlan.from_config(plan.to_config())
        assert back.signature() == plan.signature()
        assert back == plan
        other = plan.replace(precision=None)
        assert other.precision is None
        assert other.compute_layout == "NHWC"
        assert other != plan

    def test_plan_validation(self):
        with pytest.raises(ValueError):
            TuningPlan(compute_layout="NCWH")
        with pytest.raises(ValueError):
            TuningPlan(steps_per_dispatch=0)
        with pytest.raises(ValueError):
            TuningPlan(prefetch=-1)
        with pytest.raises(ValueError):
            TuningSpace({"bogus_axis": (1, 2)})

    def test_neighbors_differ_in_exactly_one_axis(self):
        space = TuningSpace.for_model(max_steps_per_dispatch=16)
        base = space.default_plan()
        base_cfg = base.to_config()
        for axis, nb in space.neighbors(base, list(AXES)):
            diff = [k for k, v in nb.to_config().items()
                    if base_cfg.get(k) != v]
            assert diff == [axis]

    def test_axis_priority_offender_seeded(self):
        assert axis_priority(None) == list(AXES)
        conv = SimpleNamespace(
            top_offenders=lambda n: ["conv2d_nchw fwd", "maxpool"])
        order = axis_priority(conv)
        assert order[0] == "compute_layout"
        mm = SimpleNamespace(top_offenders=lambda n: ["dense matmul"])
        assert axis_priority(mm)[0] == "precision"


# ----------------------------------------------------------- the records
class TestTuningRecords:

    def test_put_lookup_roundtrip(self, store):
        plan = TuningPlan(compute_layout="NHWC", steps_per_dispatch=4)
        rec = trecords.TuningRecord("fp-abc", plan, cost_s=0.010,
                                    default_cost_s=0.015, trials=12,
                                    model_name="tiny")
        path = trecords.put(rec)
        assert path is not None and os.path.exists(path)
        assert os.path.basename(path).startswith("tr_")
        got = trecords.lookup("fp-abc")
        assert got is not None
        assert got.plan.signature() == plan.signature()
        assert got.speedup == pytest.approx(1.5)
        assert got.model_name == "tiny"

    def test_key_isolation_mesh_backend_fp(self, store):
        plan = TuningPlan()
        trecords.put(trecords.TuningRecord("fp-a", plan, cost_s=0.01))
        assert trecords.lookup("fp-a") is not None
        # a different mesh, backend, or fingerprint never cross-applies
        assert trecords.lookup("fp-a", mesh="data=8") is None
        assert trecords.lookup("fp-a", backend="tpu") is None
        assert trecords.lookup("fp-b") is None

    def test_corrupt_record_quarantined(self, store):
        plan = TuningPlan(precision="bf16")
        path = trecords.put(
            trecords.TuningRecord("fp-q", plan, cost_s=0.01))
        raw = open(path, "rb").read()
        with open(path, "wb") as f:          # flip payload bytes
            f.write(raw[:-8] + b"XXXXXXXX")
        with pytest.warns(UserWarning, match="quarantine"):
            assert trecords.lookup("fp-q") is None
        names = os.listdir(store)
        assert any(n.startswith("quarantine_") for n in names)
        assert not any(n.startswith("tr_") for n in names)

    def test_disabled_store_is_inert(self, store):
        trecords.configure(None)
        with pytest.warns(UserWarning, match="disabled"):
            assert trecords.put(
                trecords.TuningRecord("fp-x", TuningPlan(),
                                      cost_s=0.01)) is None
        assert trecords.lookup("fp-x") is None
        assert trecords.record_dir() is None

    def test_mesh_signature_forms(self):
        assert trecords.mesh_signature(None) == "none"
        assert trecords.mesh_signature("data=8") == "data=8"
        from deeplearning4j_tpu.parallel.mesh import DeviceMesh
        sig = trecords.mesh_signature(DeviceMesh.data_parallel())
        assert "=" in sig                    # axis=size form, stable
        assert sig == trecords.mesh_signature(DeviceMesh.data_parallel())

    def test_fingerprint_is_seam_neutral(self):
        """Applying a plan stamps compute_layout/data_format into the
        config — the record-store identity must NOT move, or the record
        would stop matching the very model it tuned."""
        net = tiny_net()
        fp = trecords.model_fingerprint(net)
        TuningPlan(compute_layout="NHWC", fuse_epilogues=True,
                   precision="bf16").apply(net)
        assert trecords.model_fingerprint(net) == fp
        # a genuinely different architecture still gets its own key
        other = MultiLayerNetwork(
            (NeuralNetConfiguration.Builder().seed(7).weightInit("relu")
             .list()
             .layer(DenseLayer(nOut=16, activation="relu"))
             .layer(OutputLayer(nOut=4, lossFunction="mcxent",
                                activation="softmax"))
             .setInputType(InputType.feedForward(8)).build())).init()
        assert trecords.model_fingerprint(other) != fp

    def test_auto_apply_warns_once_per_key(self, store):
        net = tiny_net()
        with pytest.warns(UserWarning, match="no tuning record"):
            assert trecords.auto_apply(net) is None
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert trecords.auto_apply(net) is None   # same key: silent
        assert not [x for x in w
                    if "no tuning record" in str(x.message)]
        trecords.reset_warned()
        with pytest.warns(UserWarning, match="no tuning record"):
            trecords.auto_apply(net)


# ---------------------------------------------------- the search driver
TARGET = TuningPlan(compute_layout="NHWC", fuse_epilogues=True,
                    steps_per_dispatch=4, precision="bf16", prefetch=0)
_COST_AXES = ("compute_layout", "fuse_epilogues", "steps_per_dispatch",
              "precision", "prefetch")


def planted_cost(plan):
    """Monotone planted-optimum landscape: every axis matching TARGET
    shaves 12% — greedy refinement provably climbs to the optimum."""
    matches = sum(getattr(plan, a) == getattr(TARGET, a)
                  for a in _COST_AXES)
    return 1.0 - 0.12 * matches


class TestDriver:

    def test_finds_planted_optimum(self):
        space = TuningSpace({"compute_layout": ("NCHW", "NHWC"),
                             "fuse_epilogues": (False, True),
                             "steps_per_dispatch": (1, 4),
                             "precision": (None, "bf16"),
                             "prefetch": (0, 2)})
        calls = []

        def trial(plan):
            calls.append(plan.signature())
            return planted_cost(plan)

        res = tdriver.tune(object(), None, None, budget=48, reps=1,
                           space=space, trial_fn=trial,
                           parity_fn=lambda p: True, persist=False)
        assert res.best_plan == TARGET
        assert res.best_cost_s == pytest.approx(0.4)
        assert res.default_cost_s == pytest.approx(1.0)
        assert res.speedup == pytest.approx(2.5)
        assert len(calls) <= 48
        assert len(calls) == len(set(calls))   # no duplicate measurement

    def test_budget_respected_and_refinement_runs(self):
        space = TuningSpace.for_model(max_steps_per_dispatch=16)
        calls = []

        def trial(plan):
            calls.append(plan.signature())
            return planted_cost(plan)

        res = tdriver.tune(object(), None, None, budget=24, reps=1,
                           space=space, trial_fn=trial,
                           parity_fn=lambda p: True, persist=False)
        assert len(calls) <= 24
        assert len(calls) == len(set(calls))
        assert res.best_cost_s < res.default_cost_s
        phases = {t.phase for t in res.trials}
        assert "default" in phases and "explore" in phases
        assert "refine" in phases              # greedy walk actually ran

    def test_parity_gate_rejects_back_to_default(self):
        space = TuningSpace({"precision": (None, "bf16")})
        res = tdriver.tune(object(), None, None, budget=4, reps=1,
                           space=space, trial_fn=planted_cost,
                           parity_fn=lambda p: False, persist=False)
        assert res.best_plan == space.default_plan()
        assert res.rejected
        plan, reason = res.rejected[0]
        assert "loss parity" in reason
        assert plan.precision == "bf16"

    def test_baseline_failure_raises(self):
        def broken(plan):
            raise ValueError("no device")
        with pytest.raises(RuntimeError, match="baseline"):
            tdriver.tune(object(), None, None, budget=4,
                         space=TuningSpace({"prefetch": (0, 2)}),
                         trial_fn=broken, persist=False)

    def test_real_search_persists_record(self, store):
        x, y = tiny_data()
        space = TuningSpace({"steps_per_dispatch": (1, 2)})
        res = tdriver.tune(lambda: tiny_net(), x, y, budget=3, reps=1,
                           base_steps=2, space=space,
                           parity_guard=False, model_name="tiny")
        assert res.record is not None
        assert any(n.startswith("tr_") for n in os.listdir(store))
        got = trecords.lookup(tiny_net())     # a fresh, equal-config net
        assert got is not None
        assert got.plan.signature() == res.best_plan.signature()
        assert got.trials == len(res.trials)

    def test_loss_parity_gate_real_curves(self):
        x, y = tiny_data()
        factory = lambda: tiny_net(seed=5)    # noqa: E731
        # NHWC is the bit-compatible seam: parity must hold
        assert tdriver.loss_parity(factory, TuningPlan("NHWC"), x, y,
                                   steps=3)

        class BrokenPlan(TuningPlan):
            """A plan whose apply() perturbs the weights — numerics
            diverge and the gate must reject it."""
            def apply(self, model):
                ds = DataSet(x, y)
                for _ in range(4):
                    model.fit(ds)
                return super().apply(model)

        assert not tdriver.loss_parity(factory, BrokenPlan(), x, y,
                                       steps=3)


# -------------------------------------------------- fit-level auto-apply
class TestApplyTunedPlan:

    def test_plan_instance_applies_direct(self):
        net = tiny_net()
        plan = TuningPlan(compute_layout="NHWC", fuse_epilogues=True,
                          steps_per_dispatch=4, prefetch=0)
        k, p = stepping.apply_tuned_plan(net, plan, 1, 2)
        assert (k, p) == (4, 0)
        assert net._compute_layout == "NHWC"
        assert net._fuse_epilogues is True

    def test_caller_overrides_win(self):
        net = tiny_net()
        plan = TuningPlan(steps_per_dispatch=4, prefetch=0)
        # a caller who explicitly set k keeps it; defaults yield to plan
        k, p = stepping.apply_tuned_plan(net, plan, 2, 2)
        assert (k, p) == (2, 0)
        k, p = stepping.apply_tuned_plan(net, plan, 1, 4)
        assert (k, p) == (4, 4)

    def test_bad_value_raises(self):
        with pytest.raises(ValueError, match="TuningPlan"):
            stepping.apply_tuned_plan(tiny_net(), "bogus", 1, 2)

    def test_auto_consults_store(self, store):
        net = tiny_net()
        plan = TuningPlan(compute_layout="NHWC", steps_per_dispatch=2)
        trecords.put(trecords.TuningRecord(
            trecords.model_fingerprint(net), plan, cost_s=0.01))
        k, p = stepping.apply_tuned_plan(net, "auto", 1, 2)
        assert k == 2
        assert net._compute_layout == "NHWC"


# --------------------------------------------- end-to-end apply surfaces
class TestAutoApplyEndToEnd:

    def _seed_record(self, net, mesh=None, k=2):
        plan = TuningPlan(compute_layout="NHWC", fuse_epilogues=True,
                          steps_per_dispatch=k, prefetch=0)
        trecords.put(trecords.TuningRecord(
            trecords.model_fingerprint(net), plan, cost_s=0.005,
            default_cost_s=0.010, mesh=mesh))
        return plan

    def test_fit_auto_applies_with_zero_churn(self, store):
        net = tiny_net()
        plan = self._seed_record(net)
        x, y = tiny_data()
        batches = [DataSet(x, y)] * plan.steps_per_dispatch
        net.fit(batches, tune="auto")
        assert net._compute_layout == "NHWC"
        assert net._fuse_epilogues is True
        # steady state: repeated tuned fits re-hit the SAME record (the
        # seam-neutral fingerprint) and add NO new step signatures
        det = _churn.get_churn_detector()
        det.reset()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            net.fit(batches, tune="auto")
            net.fit(batches, tune="auto")
        assert not [x for x in w if "no tuning record" in str(x.message)]
        counts = [det.signature_count(s, owner=net)
                  for s in ("MultiLayerNetwork.fit",
                            "MultiLayerNetwork.megastep")]
        assert all(c <= 1 for c in counts)
        assert any(c == 1 for c in counts)

    def test_warmup_tuned_applies_plan(self, store):
        net = tiny_net()
        self._seed_record(net)
        cc.warmup(net, [((4, 3, 8, 8), (4, 4))], tuned=True)
        assert net._compute_layout == "NHWC"
        assert net._fuse_epilogues is True

    def test_registry_load_tuned_applies_plan(self, store):
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        reg = ModelRegistry()
        try:
            net = tiny_net()
            # the record is keyed under the REGISTRY's mesh — a plan
            # tuned for another mesh must not cross-apply
            self._seed_record(net, mesh=reg.mesh)
            with pytest.warns(UserWarning, match="W111"):
                # warm=False on the first version rolls unwarmed — the
                # W111 lint is expected and not under test here
                ver = reg.load("tuned-model", net, warm=False,
                               tuned=True)
            assert ver == 1
            assert net._compute_layout == "NHWC"
            assert net._fuse_epilogues is True
        finally:
            reg.close()


# --------------------------------------------- proactive conv-stack lint
class TestConvStackLint:

    def _located(self, n=3, fmt=None):
        out = []
        for i in range(n):
            layer = ConvolutionLayer(kernelSize=(3, 3), nOut=8,
                                     activation="relu")
            if fmt is not None:
                layer.data_format = fmt      # the NHWC seam's stamp
            out.append((f"layer[{i}]", layer))
        return out

    def test_fires_on_tpu_backend(self):
        diags = _layout.lint_conv_stack(self._located(3), backend="tpu")
        assert len(diags) == 1
        d = diags[0]
        assert d.code == "DL4J-W101"
        assert "3 conv layers" in d.message
        assert "relayout" in d.message
        assert "tune" in d.fix_hint          # points at the autotuner

    def test_silent_off_tpu_and_when_nhwc(self):
        located = self._located(3)
        assert _layout.lint_conv_stack(located, backend="cpu") == []
        assert _layout.lint_conv_stack(located, backend=None) == []
        # config-level NHWC declaration
        assert _layout.lint_conv_stack(located, compute_layout="NHWC",
                                       backend="tpu") == []
        # per-layer NHWC stamp (what an applied plan sets)
        assert _layout.lint_conv_stack(self._located(3, fmt="NHWC"),
                                       backend="tpu") == []
        # a single conv is dispatch noise, not a stack
        assert _layout.lint_conv_stack(self._located(1),
                                       backend="tpu") == []

    def test_validate_flags_then_clean_after_seam(self):
        # two convs: enough of a stack for the proactive lint
        net = MultiLayerNetwork(
            (NeuralNetConfiguration.Builder().seed(7).weightInit("relu")
             .list()
             .layer(ConvolutionLayer(kernelSize=(3, 3), padding=(1, 1),
                                     nOut=8, activation="relu"))
             .layer(ConvolutionLayer(kernelSize=(3, 3), padding=(1, 1),
                                     nOut=8, activation="relu"))
             .layer(OutputLayer(nOut=4, lossFunction="mcxent",
                                activation="softmax"))
             .setInputType(InputType.convolutional(8, 8, 3))
             .build())).init()
        with mock.patch.object(_layout, "_default_backend",
                               return_value="tpu"):
            report = net.validate()
            hits = [d for d in report if d.code == "DL4J-W101"
                    and "relayout" in d.message]
            assert hits
            net.setComputeLayout("NHWC")
            report = net.validate()
            assert not [d for d in report if d.code == "DL4J-W101"
                        and "relayout" in d.message]


# ------------------------------------------------------ CLI + acceptance
def _run_cli(args, env):
    return subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.tune"] + args,
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)


class TestCLI:

    def test_cli_tunes_persists_and_fresh_process_applies(self, tmp_path):
        """The ISSUE-17 acceptance path: the CLI search finds a plan no
        worse than the default, persists it, and a FRESH process's
        ``fit(tune="auto")`` applies it with zero cold compiles (tuning
        record + disk compile cache both hit)."""
        rdir, cdir = str(tmp_path / "records"), str(tmp_path / "cc")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = _run_cli(["lenet", "--budget", "8", "--batch", "4",
                         "--hw", "32", "--classes", "10", "--reps", "1",
                         "--steps", "2", "--dir", rdir,
                         "--cache-dir", cdir, "--no-parity", "--json"],
                        env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        payload = json.loads(proc.stdout)
        assert payload["model"] == "LeNet"
        assert payload["trials"] == 8
        assert payload["best_ms_per_step"] <= payload["default_ms_per_step"]
        assert payload["speedup"] >= 1.0
        assert payload["persisted"] is True
        assert any(n.startswith("tr_") for n in os.listdir(rdir))
        assert any(n.startswith("cc_") for n in os.listdir(cdir))

        script = tmp_path / "fresh_apply.py"
        script.write_text(f"""
import numpy as np
import sys
sys.path.insert(0, {REPO!r})
from deeplearning4j_tpu.nn import compilecache as cc
from deeplearning4j_tpu.tune import records
from deeplearning4j_tpu.models.zoo import LeNet
from deeplearning4j_tpu.data.dataset import DataSet

records.configure({rdir!r})
cc.configure({cdir!r})
net = LeNet(seed=11, num_classes=10, input_shape=(3, 32, 32)).init()
plan = records.best_plan(net)
assert plan is not None, "fresh process found no tuning record"
rng = np.random.RandomState(0)
x = rng.randn(4, 3, 32, 32).astype(np.float32)
y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 4)]
batches = [DataSet(x, y)] * max(1, plan.steps_per_dispatch)
net.fit(batches, tune="auto")
assert net._compute_layout == plan.compute_layout
stats = cc.cache_stats()
assert stats["compile_seconds"]["cold_compiles"] == 0, stats
assert stats["disk"]["hits"] >= 1, stats
print("FRESH-OK", plan.signature())
""")
        proc2 = subprocess.run([sys.executable, str(script)], cwd=REPO,
                               env=env, capture_output=True, text=True,
                               timeout=240)
        assert proc2.returncode == 0, \
            proc2.stderr[-2000:] + proc2.stdout[-500:]
        assert "FRESH-OK" in proc2.stdout

    @pytest.mark.slow
    def test_resnet50_budget_20_reduces_step_time(self, tmp_path):
        """The headline acceptance run: ``python -m
        deeplearning4j_tpu.tune resnet50 --budget 20`` (CPU-sized
        input) finds a measurably faster plan and persists it."""
        rdir, cdir = str(tmp_path / "records"), str(tmp_path / "cc")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.tune", "resnet50",
             "--budget", "20", "--batch", "2", "--hw", "32",
             "--classes", "10", "--reps", "1", "--steps", "2",
             "--dir", rdir, "--cache-dir", cdir, "--no-parity",
             "--json"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=3600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        payload = json.loads(proc.stdout)
        assert payload["trials"] == 20
        assert payload["persisted"] is True
        # the tentpole claim: search finds a measurably faster plan
        assert payload["best_ms_per_step"] < payload["default_ms_per_step"]
        assert payload["speedup"] > 1.0
