"""Multi-step on-device dispatch (lax.scan megasteps) + device prefetch.

The hard guarantee under test (ISSUE 2): ``fit(steps_per_dispatch=K)``
produces the SAME params/opt-state/per-step losses as K single-step
``fit`` calls — same fold_in RNG per iteration, same updater math, same
frozen-layer gating — while dispatching ONE compiled program per K steps.
Plus: DevicePrefetcher staging/shutdown, AsyncDataSetIterator close(),
megabatch grouping edge cases, and the profiler seams.
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import profiler
from deeplearning4j_tpu.data import (AsyncDataSetIterator, DataSet,
                                     DevicePrefetcher, IterableDataSetIterator,
                                     ListDataSetIterator, MultiDataSet)
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.graph import ComputationGraph, ElementWiseVertex
from deeplearning4j_tpu.nn.layers import (DenseLayer, DropoutLayer, LSTM,
                                          OutputLayer, RnnOutputLayer,
                                          SimpleRnn)
from deeplearning4j_tpu.train import ScoreIterationListener, updaters
from deeplearning4j_tpu.train import stepping


def mlp_conf(seed=42, lr=0.05, dropout=False):
    b = (NeuralNetConfiguration.Builder().seed(seed)
         .updater(updaters.Adam(lr)).list()
         .layer(DenseLayer(nOut=16, activation="relu")))
    if dropout:
        b = b.layer(DropoutLayer(0.5))
    return (b.layer(DenseLayer(nOut=16, activation="relu"))
            .layer(OutputLayer(nOut=3, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(4))
            .build())


def make_batches(n, batch=16, nin=4, nout=3, seed=0):
    rng = np.random.RandomState(seed)
    return [DataSet(rng.randn(batch, nin).astype(np.float32),
                    np.eye(nout, dtype=np.float32)[rng.randint(0, nout, batch)])
            for _ in range(n)]


def masked_rnn_batches(n, batch=8, C=2, T=6, seed=3):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = rng.randn(batch, C, T).astype(np.float32)
        y = (x.sum(axis=1, keepdims=True) > 0).astype(np.float32)
        labels = np.concatenate([y, 1 - y], axis=1)
        lengths = rng.randint(3, T + 1, batch)
        mask = (np.arange(T)[None, :] < lengths[:, None]).astype(np.float32)
        out.append(DataSet(x, labels, features_mask=mask, labels_mask=mask))
    return out


def rnn_conf(seed=2):
    return (NeuralNetConfiguration.Builder().seed(seed)
            .updater(updaters.Adam(0.02)).list()
            .layer(SimpleRnn(nOut=8))
            .layer(RnnOutputLayer(nOut=2, lossFunction="mcxent",
                                  activation="softmax"))
            .setInputType(InputType.recurrent(2, 6))
            .build())


def fit_singly(net, batches):
    for ds in batches:
        net.fit(ds)
    return net


class TestMultiStepEquivalence:
    def test_params_match_k_single_steps(self):
        batches = make_batches(8)
        a = MultiLayerNetwork(mlp_conf()).init()
        a.fit(batches, steps_per_dispatch=4)
        b = fit_singly(MultiLayerNetwork(mlp_conf()).init(), batches)
        assert a.getIterationCount() == b.getIterationCount() == 8
        np.testing.assert_allclose(np.asarray(a.params()),
                                   np.asarray(b.params()),
                                   rtol=1e-5, atol=1e-6)
        # opt-state too (Adam moments)
        fa = jax.tree_util.tree_leaves(a._opt_state)
        fb = jax.tree_util.tree_leaves(b._opt_state)
        for la, lb in zip(fa, fb):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=1e-5, atol=1e-6)

    def test_per_step_losses_match(self):
        batches = make_batches(6)
        a = MultiLayerNetwork(mlp_conf()).init()
        la = ScoreIterationListener(1, out=lambda m: None)
        a.setListeners(la)
        a.fit(batches, steps_per_dispatch=3)
        b = MultiLayerNetwork(mlp_conf()).init()
        lb = ScoreIterationListener(1, out=lambda m: None)
        b.setListeners(lb)
        fit_singly(b, batches)
        assert len(la.history) == len(lb.history) == 6
        np.testing.assert_allclose(la.history, lb.history,
                                   rtol=1e-5, atol=1e-7)

    def test_masked_signature_equivalence(self):
        batches = masked_rnn_batches(4)
        a = MultiLayerNetwork(rnn_conf()).init()
        a.fit(batches, steps_per_dispatch=4)
        b = fit_singly(MultiLayerNetwork(rnn_conf()).init(), batches)
        np.testing.assert_allclose(np.asarray(a.params()),
                                   np.asarray(b.params()),
                                   rtol=1e-5, atol=1e-6)

    def test_dropout_rng_parity(self):
        """fold_in(base, t) per scanned step == per single step, so even
        stochastic nets match bit-for-bit."""
        batches = make_batches(4)
        a = MultiLayerNetwork(mlp_conf(dropout=True)).init()
        a.fit(batches, steps_per_dispatch=4)
        b = fit_singly(MultiLayerNetwork(mlp_conf(dropout=True)).init(),
                       batches)
        np.testing.assert_allclose(np.asarray(a.params()),
                                   np.asarray(b.params()),
                                   rtol=1e-6, atol=1e-7)

    def test_frozen_layers_stay_frozen(self):
        batches = make_batches(4)
        net = MultiLayerNetwork(mlp_conf()).init()
        net._frozen_layers = {0}
        before = np.asarray(net._params[0]["W"]).copy()
        net.fit(batches, steps_per_dispatch=4)
        np.testing.assert_array_equal(np.asarray(net._params[0]["W"]), before)
        # and the unfrozen layers did move
        assert float(np.abs(np.asarray(net._params[-1]["W"])).sum()) > 0

    def test_tail_and_signature_change_fall_back_to_single(self):
        # 5 batches at K=4 -> one megastep + one single step; then a batch
        # with a different shape -> single step. All equivalent.
        batches = make_batches(5) + make_batches(1, batch=12, seed=9)
        a = MultiLayerNetwork(mlp_conf()).init()
        a.fit(batches, steps_per_dispatch=4)
        b = fit_singly(MultiLayerNetwork(mlp_conf()).init(), batches)
        assert a.getIterationCount() == 6
        np.testing.assert_allclose(np.asarray(a.params()),
                                   np.asarray(b.params()),
                                   rtol=1e-5, atol=1e-6)

    def test_iterator_input_and_epochs(self):
        data = DataSet.merge(make_batches(8))
        a = MultiLayerNetwork(mlp_conf()).init()
        a.fit(ListDataSetIterator(data, 16), epochs=2, steps_per_dispatch=4)
        b = MultiLayerNetwork(mlp_conf()).init()
        b.fit(ListDataSetIterator(data, 16), epochs=2)
        assert a.getIterationCount() == b.getIterationCount() == 16
        assert a.getEpochCount() == 2
        np.testing.assert_allclose(np.asarray(a.params()),
                                   np.asarray(b.params()),
                                   rtol=1e-5, atol=1e-6)

    def test_tbptt_path_unaffected(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 2, 12).astype(np.float32)
        y = np.tile(np.array([[1, 0], [0, 1]], np.float32)[rng.randint(0, 2, 4)]
                    [:, :, None], (1, 1, 12))
        conf = (NeuralNetConfiguration.Builder().seed(5)
                .updater(updaters.Adam(0.01)).list()
                .layer(LSTM(nOut=6))
                .layer(RnnOutputLayer(nOut=2, lossFunction="mcxent",
                                      activation="softmax"))
                .setInputType(InputType.recurrent(2, 12))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fitTBPTT(DataSet(x, y), tbptt_length=4)
        assert np.isfinite(net.score())


class TestGraphMultiStep:
    def _build(self):
        b = (NeuralNetConfiguration.Builder().seed(7)
             .updater(updaters.Adam(0.02)).graphBuilder())
        b.addInputs("in").setInputTypes(InputType.feedForward(4))
        b.addLayer("d1", DenseLayer(nOut=8, activation="relu"), "in")
        b.addLayer("d2", DenseLayer(nOut=8, activation="relu"), "d1")
        b.addVertex("add", ElementWiseVertex("Add"), "d1", "d2")
        b.addLayer("out", OutputLayer(nOut=3, lossFunction="mcxent",
                                      activation="softmax"), "add")
        b.setOutputs("out")
        return ComputationGraph(b.build())

    def test_graph_equivalence(self):
        batches = make_batches(6, batch=8)
        a = self._build().init()
        a.fit(batches, steps_per_dispatch=3)
        b = fit_singly(self._build().init(), batches)
        np.testing.assert_allclose(np.asarray(a.params()),
                                   np.asarray(b.params()),
                                   rtol=1e-5, atol=1e-6)

    def test_graph_multidataset_equivalence(self):
        batches = [MultiDataSet([d.features], [d.labels])
                   for d in make_batches(6, batch=8)]
        a = self._build().init()
        a.fit(batches, steps_per_dispatch=3)
        b = fit_singly(self._build().init(), batches)
        np.testing.assert_allclose(np.asarray(a.params()),
                                   np.asarray(b.params()),
                                   rtol=1e-5, atol=1e-6)


class TestParallelMultiStep:
    def test_wrapper_k_step_matches_single_step(self):
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        data = DataSet.merge(make_batches(8))
        a = MultiLayerNetwork(mlp_conf()).init()
        ParallelWrapper(a).fit(ListDataSetIterator(data, 16),
                               steps_per_dispatch=4)
        b = MultiLayerNetwork(mlp_conf()).init()
        ParallelWrapper(b).fit(ListDataSetIterator(data, 16))
        assert a.getIterationCount() == b.getIterationCount() == 8
        np.testing.assert_allclose(np.asarray(a.params()),
                                   np.asarray(b.params()),
                                   rtol=1e-5, atol=1e-6)

    def test_wrapper_prefetch_zero_stays_synchronous(self):
        """prefetch_buffer=0 must keep iterator consumption on the calling
        thread in the K-step path too (thread-affine data sources)."""
        import threading
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        main = threading.get_ident()
        seen = []

        class AffineIterator(ListDataSetIterator):
            def next(self):
                seen.append(threading.get_ident())
                return super().next()

        data = DataSet.merge(make_batches(4))
        a = MultiLayerNetwork(mlp_conf()).init()
        ParallelWrapper(a, prefetch_buffer=0).fit(
            AffineIterator(data, 16), steps_per_dispatch=2)
        assert seen and all(t == main for t in seen)
        b = MultiLayerNetwork(mlp_conf()).init()
        ParallelWrapper(b, prefetch_buffer=0).fit(AffineIterator(data, 16))
        np.testing.assert_allclose(np.asarray(a.params()),
                                   np.asarray(b.params()),
                                   rtol=1e-5, atol=1e-6)

    def test_fit_prefetch_zero_synchronous_equivalence(self):
        batches = make_batches(6)
        a = MultiLayerNetwork(mlp_conf()).init()
        a.fit(batches, steps_per_dispatch=3, prefetch=0)
        b = fit_singly(MultiLayerNetwork(mlp_conf()).init(), batches)
        np.testing.assert_allclose(np.asarray(a.params()),
                                   np.asarray(b.params()),
                                   rtol=1e-5, atol=1e-6)

    def test_wrapper_k_step_sharded_over_mesh(self):
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        if len(jax.devices()) < 2:
            pytest.skip("needs the virtual multi-device mesh")
        data = DataSet.merge(make_batches(4))
        net = MultiLayerNetwork(mlp_conf()).init()
        ParallelWrapper(net).fit(ListDataSetIterator(data, 16),
                                 steps_per_dispatch=2)
        assert net.getIterationCount() == 4
        assert np.isfinite(net.score())


class TestMegabatchGrouping:
    def test_group_counts(self):
        batches = make_batches(7)
        items = list(stepping.group_into_megabatches(iter(batches), 3))
        megas = [i for i in items if isinstance(i, stepping.MegaBatch)]
        singles = [i for i in items if isinstance(i, DataSet)]
        assert len(megas) == 2 and len(singles) == 1
        assert all(m.steps == 3 for m in megas)
        assert megas[0].features.shape == (3, 16, 4)

    def test_k1_passthrough(self):
        batches = make_batches(3)
        assert list(stepping.group_into_megabatches(iter(batches), 1)) == batches

    def test_signature_change_flushes_pending(self):
        batches = make_batches(2) + make_batches(2, batch=8, seed=5)
        items = list(stepping.group_into_megabatches(iter(batches), 3))
        # no group reaches 3: everything falls through as singles
        assert all(isinstance(i, DataSet) for i in items)
        assert len(items) == 4


class TestDevicePrefetcher:
    def test_yields_staged_megabatches(self):
        batches = make_batches(4)
        with DevicePrefetcher(iter(batches), steps_per_dispatch=2) as pf:
            items = list(pf)
        assert len(items) == 2
        assert all(isinstance(m, stepping.MegaBatch) for m in items)
        assert all(isinstance(m.features, jax.Array) for m in items)
        assert items[0].features.shape == (2, 16, 4)

    def test_stages_single_datasets_too(self):
        batches = make_batches(3)
        with DevicePrefetcher(iter(batches), steps_per_dispatch=2) as pf:
            items = list(pf)
        assert isinstance(items[-1], DataSet)
        assert isinstance(items[-1].features, jax.Array)

    def test_close_is_idempotent_and_stops_worker(self):
        pf = DevicePrefetcher(iter(make_batches(64)), steps_per_dispatch=2,
                              prefetch=1)
        next(pf)
        pf.close()
        pf.close()
        assert pf._thread is None
        with pytest.raises(StopIteration):
            next(pf)

    def test_worker_error_propagates(self):
        def bad():
            yield make_batches(1)[0]
            raise RuntimeError("boom")
        with DevicePrefetcher(bad(), steps_per_dispatch=1) as pf:
            next(pf)
            with pytest.raises(RuntimeError, match="boom"):
                while True:
                    next(pf)

    def test_h2d_bytes_counter_increments(self):
        reg = profiler.get_registry()
        c = reg.get("dl4j_prefetch_h2d_bytes_total")
        before = c.value
        profiler.set_profiling_mode(profiler.ProfilingMode.BASIC)
        try:
            with DevicePrefetcher(iter(make_batches(2)),
                                  steps_per_dispatch=2) as pf:
                list(pf)
        finally:
            profiler.set_profiling_mode(None)
        assert c.value > before

    def test_queue_depth_gauge_registered(self):
        assert profiler.get_registry().get("dl4j_prefetch_queue_depth") is not None


class TestAsyncIteratorLifecycle:
    def test_close_and_context_manager(self):
        it = AsyncDataSetIterator(
            ListDataSetIterator(DataSet.merge(make_batches(4)), 16))
        assert it.hasNext()
        it.close()
        assert not it.hasNext()
        assert it._thread is None
        it.close()  # idempotent
        with AsyncDataSetIterator(
                ListDataSetIterator(DataSet.merge(make_batches(4)), 16)) as it2:
            n = sum(1 for _ in it2)
            assert n == 4
        assert it2._thread is None

    def test_base_iterator_error_propagates(self):
        """A failing base iterator must raise on the consumer side, not
        silently truncate the stream (evaluate() now rides this path)."""
        class FailingIterator(ListDataSetIterator):
            def next(self):
                if self._pos >= self.batch_size:  # fail on batch 2
                    raise IOError("disk gone")
                return super().next()

        it = AsyncDataSetIterator(
            FailingIterator(DataSet.merge(make_batches(4)), 16))
        with it:
            got = [it.next()]
            with pytest.raises(IOError, match="disk gone"):
                while it.hasNext():
                    got.append(it.next())
        assert len(got) == 1

    def test_reset_after_close_restarts(self):
        it = AsyncDataSetIterator(
            ListDataSetIterator(DataSet.merge(make_batches(2)), 16))
        it.close()
        it.reset()
        assert it.hasNext()
        assert sum(1 for _ in it) == 2
        it.close()

    def test_queue_depth_gauge_registered(self):
        assert profiler.get_registry().get(
            "dl4j_async_iterator_queue_depth") is not None


class TestEvaluateBulkPull:
    def test_evaluate_accepts_plain_list(self):
        batches = make_batches(4)
        net = MultiLayerNetwork(mlp_conf()).init()
        ev = net.evaluate(batches)
        assert 0.0 <= ev.accuracy() <= 1.0

    def test_evaluate_prefetch_false_stays_synchronous(self):
        import threading
        main = threading.get_ident()
        seen = []

        class AffineIterator(ListDataSetIterator):
            def next(self):
                seen.append(threading.get_ident())
                return super().next()

        net = MultiLayerNetwork(mlp_conf()).init()
        it = AffineIterator(DataSet.merge(make_batches(3)), 16)
        ev = net.evaluate(it, prefetch=False)
        assert seen and all(t == main for t in seen)
        assert 0.0 <= ev.accuracy() <= 1.0

    def test_evaluate_accepts_generator(self):
        batches = make_batches(3)
        net = MultiLayerNetwork(mlp_conf()).init()
        ev = net.evaluate(iter(batches))
        assert 0.0 <= ev.accuracy() <= 1.0

    def test_evaluate_matches_reference_loop(self):
        split = make_batches(4)
        net = MultiLayerNetwork(mlp_conf()).init()
        net.fit(split, steps_per_dispatch=2)
        from deeplearning4j_tpu.evaluation import Evaluation
        ref = Evaluation()
        for ds in split:
            ref.eval(ds.labels, np.asarray(net.output(ds.features)))
        ev = net.evaluate(ListDataSetIterator(DataSet.merge(split), 16))
        assert ev.accuracy() == pytest.approx(ref.accuracy())

    def test_evaluate_regression_bulk(self):
        rng = np.random.RandomState(0)
        batches = [DataSet(rng.randn(8, 4).astype(np.float32),
                           rng.randn(8, 3).astype(np.float32))
                   for _ in range(3)]
        conf = (NeuralNetConfiguration.Builder().seed(1)
                .updater(updaters.Adam(0.01)).list()
                .layer(DenseLayer(nOut=8, activation="tanh"))
                .layer(OutputLayer(nOut=3, lossFunction="mse",
                                   activation="identity"))
                .setInputType(InputType.feedForward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        ev = net.evaluateRegression(batches)
        assert np.isfinite(ev.meanSquaredError())

    def test_iterable_adapter(self):
        batches = make_batches(3)
        it = IterableDataSetIterator(batches)
        assert it.hasNext()
        assert sum(1 for _ in it) == 3
        it.reset()
        assert it.hasNext()

    def test_generator_evaluates_every_batch(self):
        """One-shot generators must not lose the buffered first batch to
        the AsyncDataSetIterator wrapper's constructor reset()."""
        batches = make_batches(3)
        seen = []
        it = AsyncDataSetIterator(
            IterableDataSetIterator(ds for ds in batches))
        with it:
            while it.hasNext():
                seen.append(it.next())
        assert len(seen) == 3
        np.testing.assert_array_equal(seen[0].features, batches[0].features)


class TestProfilerSeams:
    def test_megastep_records_span_and_gauge(self):
        profiler.set_profiling_mode(profiler.ProfilingMode.BASIC)
        profiler.enable_tracing()
        try:
            reg = profiler.get_registry()
            h = reg.histogram("dl4j_train_step_seconds",
                              "Compiled train-step dispatch time per iteration")
            c0 = h.count
            net = MultiLayerNetwork(mlp_conf()).init()
            net.fit(make_batches(4), steps_per_dispatch=4)
            assert h.count == c0 + 1  # ONE dispatch for 4 steps
            g = reg.get("dl4j_steps_per_dispatch")
            assert g is not None and g.value == 4
            # megastep advances the iterations counter by K per dispatch
            assert reg.get("dl4j_train_iterations_total").value >= 4
            names = [e["name"] for e in profiler.get_tracer().events()]
            assert "train:megastep" in names
            # a single-step dispatch resets the amortization gauge so
            # per-step derivations from dl4j_train_step_seconds stay right
            net.fit(make_batches(1))
            assert g.value == 1
        finally:
            profiler.set_profiling_mode(None)
            profiler.disable_tracing()
            profiler.get_tracer().clear()
