"""Whole-program static cost model (ISSUE 19): chip registry, the
hand-computed MLP liveness pin (fp32 and bf16+masters), a bad-fixture /
clean-bill pair per DL4J-E12x/W12x code, the roofline/capacity planner,
the E104/W109 supersession, the measured-profile W105 satellite, the
tune/ static pruner, bench calibration, the CLI, and the jax-blocked
subprocess pin."""

import importlib.util
import json
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.analysis import (DIAGNOSTIC_CODES, MeshSpec,
                                         StageProfile, analyze)
from deeplearning4j_tpu.analysis import cost as C
from deeplearning4j_tpu.analysis.chipspec import (CHIP_REGISTRY, ChipSpec,
                                                  chip_names)
from deeplearning4j_tpu.nn.config import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.train.updaters import Adam, Sgd

REPO = pathlib.Path(__file__).resolve().parent.parent

#: chip fixtures — deliberately extreme so each code's trigger is
#: unambiguous (the registry chips are the clean-bill side)
TINY = {"name": "tiny", "peak_flops": 1e12, "hbm_gb": 0.001,
        "hbm_gbps": 10.0, "ici_gbps": 1.0}
ONEGB = {"name": "onegb", "peak_flops": 1e12, "hbm_gb": 1.0,
         "hbm_gbps": 100.0, "ici_gbps": 10.0}
SLOWICI = {"name": "slowici", "peak_flops": 1e12, "hbm_gb": 32.0,
           "hbm_gbps": 1000.0, "ici_gbps": 0.001}

B = 32
#: Dense(784->512) + Dense(512->256) + Output(256->10), biases included
P = (784 * 512 + 512) + (512 * 256 + 256) + (256 * 10 + 10)
ACT_ELEMS = 784 + 512 + 256 + 10      # input held for dW + every output


def _mlp(updater=None):
    return (NeuralNetConfiguration.Builder().seed(7)
            .updater(updater or Adam(1e-3)).weightInit("xavier").list()
            .layer(DenseLayer(nOut=512, activation="relu"))
            .layer(DenseLayer(nOut=256, activation="relu"))
            .layer(OutputLayer(nOut=10, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(784)).build())


def _codes(diags):
    return [d.code for d in diags]


# ============================================================ chip registry
class TestChipSpec:
    def test_registry_covers_target_generations(self):
        assert {"tpu-v3", "tpu-v4", "tpu-v5e", "cpu"} <= set(chip_names())
        v4 = CHIP_REGISTRY["tpu-v4"]
        assert v4.hbm_gb == 32.0
        assert v4.hbm_bytes == 32.0 * (1 << 30)

    def test_coerce_accepts_every_declaration_form(self):
        v4 = ChipSpec.coerce("tpu-v4")
        assert ChipSpec.coerce(v4) is v4
        assert ChipSpec.coerce(None).name == "tpu-v4"     # the default
        custom = ChipSpec.coerce(TINY)
        assert custom.name == "tiny" and custom.hbm_gb == 0.001

    def test_unknown_chip_names_known_ones(self):
        with pytest.raises(ValueError, match="tpu-v4"):
            ChipSpec.coerce("tpu-v9000")

    def test_fp32_runs_at_half_the_mxu_peak(self):
        v4 = CHIP_REGISTRY["tpu-v4"]
        assert v4.peak_for("fp32") == v4.peak_flops / 2
        assert v4.peak_for("bf16") == v4.peak_flops


# ================================================= MLP liveness pin (exact)
class TestMemoryPlanPin:
    """The memory-model conventions, pinned analytically: every component
    of the plan equals the hand-computed value, to the byte."""

    def test_fp32_adam_components_exact(self):
        mem = C.memory_plan(_mlp(), cost=C.CostSpec(chip="tpu-v4"),
                            batch_size=B)
        assert mem.components == {
            "params": P * 4, "grads": P * 4, "fp32 masters": 0,
            "updater state": P * 4 * 2,           # Adam: m + v on masters
            "live activations": B * ACT_ELEMS * 4,
            "megastep staging": 0,                # K=1: no staging
            "prefetch": 2 * B * 784 * 4,          # depth x input bytes
        }
        assert mem.peak_bytes == sum(mem.components.values())

    def test_bf16_low_precision_adds_masters(self):
        mem = C.memory_plan(_mlp(),
                            cost=C.CostSpec(chip="tpu-v4",
                                            precision="bf16"),
                            batch_size=B)
        assert mem.components == {
            "params": P * 2, "grads": P * 2,      # compute dtype
            "fp32 masters": P * 4,                # low precision: masters
            "updater state": P * 4 * 2,           # state on the masters
            "live activations": B * ACT_ELEMS * 2,
            "megastep staging": 0,
            "prefetch": 2 * B * 784 * 2,
        }

    def test_data_axis_shards_activations_not_params(self):
        base = C.memory_plan(_mlp(), batch_size=B)
        sharded = C.memory_plan(_mlp(), mesh="data=8", batch_size=B)
        assert sharded.components["params"] == base.components["params"]
        assert sharded.components["live activations"] == \
            base.components["live activations"] / 8
        assert sharded.components["prefetch"] == \
            base.components["prefetch"] / 8

    def test_megastep_staging_scales_with_k(self):
        mem = C.memory_plan(_mlp(),
                            cost=C.CostSpec(steps_per_dispatch=16,
                                            prefetch=0),
                            batch_size=B)
        assert mem.components["megastep staging"] == 16 * B * 784 * 4
        name, _ = C.memory_plan(
            _mlp(), cost=C.CostSpec(steps_per_dispatch=4096, prefetch=0),
            batch_size=B).dominating()
        assert name == "megastep staging"


# ========================================================== roofline model
class TestStepTime:
    def test_estimate_is_sane_and_bounded(self):
        est = C.step_time(_mlp(), cost=C.CostSpec(chip="tpu-v4"),
                          batch_size=B)
        assert est.step_s > 0
        assert 0 < est.mfu <= 1.0
        assert est.roofline_s >= est.compute_s > 0
        assert est.roofline_s >= est.hbm_s > 0
        assert est.bound in ("compute", "hbm bandwidth", "collectives")
        assert "predicted step" in est.format()

    def test_inference_cheaper_than_training(self):
        train = C.step_time(_mlp(), batch_size=B, train=True)
        infer = C.step_time(_mlp(), batch_size=B, train=False)
        assert infer.step_s < train.step_s
        assert infer.collective_s == 0

    def test_collectives_appear_only_with_a_data_axis(self):
        alone = C.step_time(_mlp(), batch_size=B)
        wide = C.step_time(_mlp(), mesh="data=8", batch_size=B)
        assert alone.collective_s == 0
        assert wide.collective_s > 0

    def test_per_stage_breakdown_under_pipeline(self):
        est = C.step_time(_mlp(), mesh=MeshSpec({"pipe": 2}, pipeline=2),
                          batch_size=B)
        assert est.per_stage is not None and len(est.per_stage) == 2
        assert sum(est.per_stage) == pytest.approx(est.roofline_s)


class TestCapacity:
    def test_min_replicas_is_ceil_of_qps_over_per_replica(self):
        spec = C.CostSpec(buckets=(8,), qps=1000.0)
        cap = C.capacity(_mlp(), spec)
        assert cap["bucket"] == 8
        assert cap["per_replica_qps"] == pytest.approx(
            8 / (cap["latency_ms"] / 1e3))
        assert cap["min_replicas"] == int(np.ceil(
            1000.0 / cap["per_replica_qps"]))


# ===================================== one bad fixture + clean bill per code
class TestCostLints:
    def test_e120_step_peak_overflow_and_clean_bill(self):
        bad = _codes(C.lint_cost(_mlp(), C.CostSpec(chip=TINY),
                                 batch_size=B))
        assert bad == ["DL4J-E120"]
        d = C.lint_cost(_mlp(), C.CostSpec(chip=TINY), batch_size=B)[0]
        assert "dominating" in d.message      # names the liveness term
        assert _codes(C.lint_cost(_mlp(), C.CostSpec(),
                                  batch_size=B)) == []

    def test_w120_remat_when_activations_dominate_near_budget(self):
        bad = _codes(C.lint_cost(
            _mlp(), C.CostSpec(chip=ONEGB, prefetch=0),
            batch_size=100_000))
        assert bad == ["DL4J-W120"]
        assert _codes(C.lint_cost(_mlp(), C.CostSpec(prefetch=0),
                                  batch_size=B)) == []

    def test_w121_comms_bound_needs_declared_batch(self):
        spec = C.CostSpec(chip=SLOWICI)
        bad = _codes(C.lint_cost(_mlp(), spec, mesh="data=8",
                                 batch_size=256))
        assert bad == ["DL4J-W121"]
        # same model/mesh/chip, batch undeclared: the gate holds
        assert _codes(C.lint_cost(_mlp(), spec, mesh="data=8")) == []

    def test_w122_mfu_below_declared_target(self):
        bad = _codes(C.lint_cost(_mlp(), C.CostSpec(mfu_target=0.99),
                                 batch_size=B))
        assert bad == ["DL4J-W122"]
        assert _codes(C.lint_cost(_mlp(), C.CostSpec(mfu_target=1e-9),
                                  batch_size=B)) == []

    def test_e121_serving_bucket_overflow(self):
        bad = _codes(C.lint_cost(_mlp(),
                                 C.CostSpec(chip=TINY, buckets=(8, 1024))))
        assert "DL4J-E121" in bad
        assert _codes(C.lint_cost(_mlp(),
                                  C.CostSpec(buckets=(8, 1024)))) == []

    def test_e122_capacity_shortfall_names_min_replicas(self):
        diags = C.lint_cost(_mlp(), C.CostSpec(qps=1e12, buckets=(8,)))
        assert _codes(diags) == ["DL4J-E122"]
        assert "minimal replica count" in diags[0].message
        lat = C.lint_cost(_mlp(), C.CostSpec(p99_ms=1e-9))
        assert _codes(lat) == ["DL4J-E122"]
        assert "no replica count fixes" in lat[0].message
        assert _codes(C.lint_cost(
            _mlp(), C.CostSpec(qps=1.0, p99_ms=1e6, buckets=(8,)))) == []

    def test_new_codes_documented(self):
        for code in ("DL4J-E120", "DL4J-E121", "DL4J-E122",
                     "DL4J-W120", "DL4J-W121", "DL4J-W122"):
            assert code in DIAGNOSTIC_CODES


# ================================================== analyze() integration
def _wide_mlp():
    return (NeuralNetConfiguration.Builder().seed(7).updater(Adam(1e-3))
            .weightInit("xavier").list()
            .layer(DenseLayer(nOut=4096, activation="relu"))
            .layer(DenseLayer(nOut=4096, activation="relu"))
            .layer(OutputLayer(nOut=10, lossFunction="mcxent"))
            .setInputType(InputType.feedForward(4096)).build())


class TestAnalyzeIntegration:
    def test_cost_supersedes_e104_w109_heuristics(self):
        # without cost=: the params-only heuristics fire on a 4096-wide
        # MLP over data=8 (replicated Adam state above the W109 bar)
        plain = analyze(_wide_mlp(), mesh="data=8").codes()
        assert "DL4J-W109" in plain
        # with cost=: the exact ZeRO-aware liveness plan judges updater
        # state against the DECLARED chip — the heuristics stand down
        costed = analyze(_wide_mlp(), mesh="data=8", cost="tpu-v4")
        assert "DL4J-W109" not in costed.codes()
        assert "DL4J-E104" not in costed.codes()
        assert costed.ok(warnings_as_errors=True), costed.format()

    def test_cost_diagnostics_flow_through_analyze(self):
        report = analyze(_mlp(), cost=C.CostSpec(chip=TINY), batch_size=B)
        assert "DL4J-E120" in report.codes()

    def test_cost_coercion_forms(self):
        assert analyze(_mlp(), cost=True).ok()
        assert analyze(_mlp(), cost="tpu-v5e").ok()
        assert analyze(_mlp(), cost={"chip": "tpu-v3"}).ok()

    def test_plan_report_bundles_everything(self):
        rep = C.plan(_mlp(), cost=C.CostSpec(qps=100.0, buckets=(8,)),
                     batch_size=B)
        out = rep.format()
        assert "step-peak HBM" in out
        assert "predicted step" in out
        assert "QPS/replica" in out
        assert rep.capacity["min_replicas"] >= 1

    def test_profile_without_mesh_is_a_usage_error(self):
        with pytest.raises(ValueError, match="profile"):
            analyze(_mlp(), profile=[{"layer": "x", "device_ms": 1.0}])


# ===================================== W105 measured-profile (ROADMAP carry)
def _four_dense():
    """FLOP-balanced 4-layer stack: the static model sees no imbalance,
    so any W105 must come from MEASURED time."""
    return (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(0.1))
            .weightInit("xavier").list()
            .layer(DenseLayer(nOut=512, activation="relu"))
            .layer(DenseLayer(nOut=512, activation="relu"))
            .layer(DenseLayer(nOut=512, activation="relu"))
            .layer(DenseLayer(nOut=512, activation="relu"))
            .setInputType(InputType.feedForward(512)).build())


class TestStageProfileW105:
    ROWS = [{"layer": "denselayer_0", "device_ms": 40.0},
            {"layer": "denselayer_1", "device_ms": 1.0},
            {"layer": "denselayer_2", "device_ms": 1.0},
            {"layer": "denselayer_3", "device_ms": 1.0}]

    def test_measured_profile_overrides_the_flop_model(self):
        conf = _four_dense()
        flop = analyze(conf, mesh="pipe=2,data=1", pipeline=2)
        assert "DL4J-W105" not in flop.codes()     # FLOP-balanced
        measured = analyze(conf, mesh="pipe=2,data=1", pipeline=2,
                           profile=StageProfile(self.ROWS, source="trace"))
        w105 = [d for d in measured
                if d.code == "DL4J-W105"]
        assert w105, measured.format()
        assert "measured per-stage device time" in w105[0].message
        assert "trace" in w105[0].message          # names the source
        assert "device-ms/step" in w105[0].message

    def test_flop_fallback_names_the_static_model(self):
        lop = (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(0.1))
               .weightInit("xavier").list()
               .layer(DenseLayer(nOut=2048, activation="relu"))
               .layer(DenseLayer(nOut=8, activation="relu"))
               .layer(DenseLayer(nOut=8, activation="relu"))
               .layer(OutputLayer(nOut=2))
               .setInputType(InputType.feedForward(2048)).build())
        report = analyze(lop, mesh="pipe=2,data=1", pipeline=2)
        w105 = [d for d in report if d.code == "DL4J-W105"]
        assert w105, report.format()
        assert "the static FLOP model" in w105[0].message
        assert "GFLOP/example" in w105[0].message

    def test_coerce_json_trace_path(self, tmp_path):
        trace = tmp_path / "trace.json"
        trace.write_text(json.dumps({"rows": self.ROWS,
                                     "source": "bench-r06"}))
        prof = StageProfile.coerce(str(trace))
        assert prof.source == "bench-r06"
        assert len(prof.rows) == 4
        report = analyze(_four_dense(), mesh="pipe=2,data=1", pipeline=2,
                         profile=str(trace))
        assert "DL4J-W105" in report.codes()

    def test_coerce_bad_path_raises(self):
        with pytest.raises(ValueError, match="does not exist"):
            StageProfile.coerce("/nonexistent/trace.json")

    def test_positional_fallback_without_layer_names(self):
        prof = StageProfile([{"device_ms": 40.0}, {"device_ms": 1.0},
                             {"device_ms": 1.0}, {"device_ms": 1.0}])
        report = analyze(_four_dense(), mesh="pipe=2,data=1", pipeline=2,
                         profile=prof)
        assert "DL4J-W105" in report.codes()

    def test_mismatched_profile_degrades_to_flops(self):
        prof = StageProfile([{"layer": "nosuch", "device_ms": 99.0}])
        report = analyze(_four_dense(), mesh="pipe=2,data=1", pipeline=2,
                         profile=prof)
        assert "DL4J-W105" not in report.codes()   # balanced FLOP verdict


# ================================================= tune/ static pruning
class TestTunePruning:
    TOY = {"name": "toy", "peak_flops": 1e12, "hbm_gb": 40.0 / 1024,
           "hbm_gbps": 100.0, "ici_gbps": 10.0}

    def _run(self, **kw):
        from deeplearning4j_tpu import tune as T
        space = T.TuningSpace({"steps_per_dispatch": (1, 16)})
        feats = np.zeros((1024, 784), np.float32)
        res = T.tune(_mlp(), feats, None, budget=8, reps=1, space=space,
                     trial_fn=lambda p: 1.0, parity_fn=lambda p: True,
                     persist=False, **kw)
        return space, res

    def test_dominated_candidate_pruned_with_reason(self):
        space, res = self._run(cost_spec={"chip": self.TOY})
        assert len(res.pruned) >= 1
        plans = {p.steps_per_dispatch for p, _ in res.pruned}
        assert plans == {16}                       # K=16 staging OOMs
        _, reason = res.pruned[0]
        assert "OOM" in reason and "megastep staging" in reason
        assert "pruned" in res.summary()
        # pruning spends no measurement: only the default was timed
        assert [t.plan.signature() for t in res.trials] == \
            [space.default_plan().signature()]

    def test_incumbent_default_never_pruned(self):
        space, res = self._run(cost_spec={"chip": self.TOY})
        default_sig = space.default_plan().signature()
        assert all(p.signature() != default_sig for p, _ in res.pruned)
        assert any(t.phase == "default" for t in res.trials)
        assert res.best_plan == space.default_plan()

    def test_no_cost_spec_means_no_pruning(self):
        _space, res = self._run()
        assert res.pruned == []
        assert {t.plan.steps_per_dispatch for t in res.trials} == {1, 16}

    def test_tuning_report_alias(self):
        from deeplearning4j_tpu import tune as T
        assert T.TuningReport is T.TuneResult


# ==================================================== bench calibration
class TestBenchCalibration:
    @pytest.fixture(scope="class")
    def bench(self):
        spec = importlib.util.spec_from_file_location(
            "bench", REPO / "bench.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_ratio_finite_and_stable(self, bench):
        row = bench.cost_calibration(_mlp(), batch=B,
                                     measured_step_s=0.005)
        assert row["chip"] == "tpu-v5e"
        assert row["predicted_step_ms"] > 0
        assert row["predicted_peak_hbm_mb"] > 0
        assert np.isfinite(row["cost_model_ratio"])
        assert row["cost_model_ratio"] == pytest.approx(
            0.005 / (row["predicted_step_ms"] / 1e3), rel=1e-2)
        again = bench.cost_calibration(_mlp(), batch=B,
                                       measured_step_s=0.005)
        assert again["predicted_step_ms"] == row["predicted_step_ms"]
        assert again["cost_model_ratio"] == row["cost_model_ratio"]

    def test_precision_changes_the_prediction(self, bench):
        fp32 = bench.cost_calibration(_mlp(), batch=B,
                                      measured_step_s=0.005)
        bf16 = bench.cost_calibration(_mlp(), batch=B,
                                      measured_step_s=0.005,
                                      precision="bf16")
        assert bf16["predicted_peak_hbm_mb"] != fp32["predicted_peak_hbm_mb"]


# ============================================================== serving
class TestServingCost:
    def test_server_validate_runs_serving_cost_codes(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.serving import ModelServer
        conf = (NeuralNetConfiguration.Builder().seed(42)
                .updater(Sgd(0.1)).list()
                .layer(DenseLayer(nOut=8, activation="relu"))
                .layer(OutputLayer(nOut=3, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(4)).build())
        sv = ModelServer(MultiLayerNetwork(conf).init(), batch_limit=8,
                         max_queue=32, coalesce_ms=1.0)
        try:
            nano = {"name": "nano", "peak_flops": 1e12, "hbm_gb": 1e-7,
                    "hbm_gbps": 10.0, "ici_gbps": 1.0}
            bad = sv.validate(cost={"chip": nano, "p99_ms": 1e-9})
            got = {d.code for d in bad.diagnostics}
            assert {"DL4J-E121", "DL4J-E122"} <= got, bad.format()
            clean = sv.validate(cost="tpu-v4")
            assert not [d for d in clean.diagnostics
                        if d.code.startswith(("DL4J-E12", "DL4J-W12"))]
        finally:
            sv.close()


# ========================================================= CLI acceptance
class TestCliCost:
    def test_zoo_clean_under_cost_flag(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["--zoo", "--mesh", "data=8", "--cost",
                     "--chip", "tpu-v4"]) == 0
        assert "16 model(s) linted: 16 clean" in capsys.readouterr().out

    def test_chip_implies_cost_and_validates(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        with pytest.raises(SystemExit):
            main(["LeNet", "--chip", "not-a-chip"])
        assert "known chips" in capsys.readouterr().err

    def test_profile_flag_needs_mesh(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        with pytest.raises(SystemExit):
            main(["LeNet", "--profile", "x.json"])
        assert "--mesh" in capsys.readouterr().err

    def test_repo_lint_gate_has_cost_hook(self):
        spec = importlib.util.spec_from_file_location(
            "lintmod", REPO / "tools" / "lint.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_cost() == 0


# ================================================== jax-free subprocess pin
class TestPureStaticCost:
    def test_cost_model_runs_with_jax_blocked(self):
        code = (
            "import sys\n"
            "sys.modules['jax'] = None\n"
            "sys.modules['jax.numpy'] = None\n"
            "from types import SimpleNamespace as NS\n"
            "from deeplearning4j_tpu.analysis import chipspec\n"
            "from deeplearning4j_tpu.analysis import cost as C\n"
            "class Arr:\n"
            "    def __init__(self, shape, dtype='float32'):\n"
            "        self.shape, self.dtype = shape, dtype\n"
            "class Node:\n"
            "    def __init__(self, op, ins, outs):\n"
            "        self.op, self.inputs, self.outputs = op, ins, outs\n"
            "        self.attrs = {}\n"
            "sd = NS(_nodes=[Node('matmul', ['x', 'w'], ['y'])],\n"
            "        _placeholders={'x': ((None, 4096), 'float32')},\n"
            "        _constants={},\n"
            "        _variables={'w': Arr((4096, 256))},\n"
            "        _loss_variables=[], training_config=None)\n"
            "chip = chipspec.ChipSpec.coerce('tpu-v4')\n"
            "mem = C.memory_plan(sd, cost=C.CostSpec(chip=chip),\n"
            "                    batch_size=16)\n"
            "assert mem.peak_bytes > 0, mem.components\n"
            "est = C.step_time(sd, batch_size=16)\n"
            "assert est.step_s > 0 and 0 < est.mfu <= 1\n"
            "diags = C.lint_cost(sd, C.CostSpec(\n"
            "    chip={'name': 't', 'peak_flops': 1e12, 'hbm_gb': 1e-6,\n"
            "          'hbm_gbps': 10.0, 'ici_gbps': 1.0}), batch_size=16)\n"
            "assert [d.code for d in diags] == ['DL4J-E120'], diags\n"
            "print('PURE-STATIC-COST-OK')\n")
        proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "PURE-STATIC-COST-OK" in proc.stdout
