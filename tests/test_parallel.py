"""Parallelism tests on the virtual 8-device CPU mesh.

Reference test-strategy parity (SURVEY.md §4): multi-worker simulated
in-process (the reference uses SparkContext(local[*]) + Aeron loopback;
here: an 8-device CPU mesh exercising real SPMD partitioning + collectives).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator, IrisDataSetIterator, NormalizerStandardize
from deeplearning4j_tpu.models import transformer as tfm
from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import DeviceMesh, ParallelInference, ParallelWrapper
from deeplearning4j_tpu.parallel.sequence import ring_attention, ring_attention_reference
from deeplearning4j_tpu.train import updaters


@pytest.fixture(scope="module")
def devices8():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return jax.devices()


class TestMesh:
    def test_create_shapes(self, devices8):
        m = DeviceMesh.create(data=2, model=2, seq=2)
        assert m.size() == 8
        assert m.size("data") == 2 and m.size("model") == 2 and m.size("seq") == 2
        m2 = DeviceMesh.create(data=-1, model=2)
        assert m2.size("data") == 4

    def test_shard_batch_places(self, devices8):
        m = DeviceMesh.create(data=4, model=2)
        x = np.ones((8, 3), np.float32)
        sx = m.shard_batch(x)
        assert len(sx.sharding.device_set) == 8  # data-sharded, model-replicated

    def test_sharding_rule(self, devices8):
        from deeplearning4j_tpu.parallel import ShardingRule
        m = DeviceMesh.create(data=4, model=2)
        rule = ShardingRule({r"w1": (None, "model"), r"w2": ("model", None)})
        params = {"w1": np.ones((4, 8), np.float32),
                  "w2": np.ones((8, 4), np.float32),
                  "b": np.ones((4,), np.float32)}
        out = rule.shard_params(m, params)
        assert out["w1"].sharding.spec == jax.sharding.PartitionSpec(None, "model")
        assert out["b"].sharding.spec == jax.sharding.PartitionSpec()


class TestRingAttention:
    def test_matches_exact(self, devices8):
        m = DeviceMesh.create(data=2, model=1, seq=4)
        rng = np.random.RandomState(0)
        B, T, H, D = 2, 32, 2, 8
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        ring = ring_attention(q, k, v, m.mesh)
        exact = ring_attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(exact),
                                   rtol=2e-4, atol=2e-5)

    def test_causal_matches_exact(self, devices8):
        m = DeviceMesh.create(data=1, model=1, seq=8)
        rng = np.random.RandomState(1)
        B, T, H, D = 1, 64, 2, 4
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        ring = ring_attention(q, k, v, m.mesh, is_causal=True)
        exact = ring_attention_reference(q, k, v, is_causal=True)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(exact),
                                   rtol=2e-4, atol=2e-5)

    def test_grads_flow_through_ring(self, devices8):
        m = DeviceMesh.create(data=1, model=1, seq=4, devices=jax.devices()[:4])
        rng = np.random.RandomState(2)
        B, T, H, D = 1, 16, 1, 4
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        f_ring = lambda q: jnp.sum(ring_attention(q, q, q, m.mesh) ** 2)
        f_exact = lambda q: jnp.sum(ring_attention_reference(q, q, q) ** 2)
        g_ring = jax.grad(f_ring)(q)
        g_exact = jax.grad(f_exact)(q)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_exact),
                                   rtol=1e-3, atol=1e-4)


class TestDataParallelTraining:
    def _net(self):
        conf = (NeuralNetConfiguration.Builder().seed(42)
                .updater(updaters.Adam(0.05)).list()
                .layer(DenseLayer(nOut=16, activation="relu"))
                .layer(OutputLayer(nOut=3, lossFunction="mcxent", activation="softmax"))
                .setInputType(InputType.feedForward(4))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_dp_training_matches_single_device(self, devices8):
        it = IrisDataSetIterator(150)
        ds = it.next()
        ds.shuffle(seed=0)
        norm = NormalizerStandardize()
        norm.fit(ds)
        norm.transform(ds)

        # single-device
        net1 = self._net()
        net1.fit(ListDataSetIterator(ds, 40), epochs=5)

        # 8-way data parallel: same data, same seed → same result
        net2 = self._net()
        pw = ParallelWrapper(net2, DeviceMesh.data_parallel())
        pw.fit(ListDataSetIterator(ds, 40), epochs=5)

        x = ds.features[:16]
        np.testing.assert_allclose(np.asarray(net1.output(x)),
                                   np.asarray(net2.output(x)), rtol=2e-3, atol=1e-4)

    def test_dp_handles_uneven_batch(self, devices8):
        net = self._net()
        pw = ParallelWrapper(net, DeviceMesh.data_parallel())
        rng = np.random.RandomState(0)
        ds = DataSet(rng.randn(13, 4).astype(np.float32),  # 13 % 8 != 0
                     np.eye(3, dtype=np.float32)[rng.randint(0, 3, 13)])
        pw.fit(ListDataSetIterator(ds, 13), epochs=1)
        assert np.isfinite(net.score())


class TestParallelInference:
    def test_batched_requests(self, devices8):
        conf = (NeuralNetConfiguration.Builder().seed(1)
                .updater(updaters.Sgd(0.1)).list()
                .layer(DenseLayer(nOut=8, activation="relu"))
                .layer(OutputLayer(nOut=3, lossFunction="mcxent", activation="softmax"))
                .setInputType(InputType.feedForward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        pi = ParallelInference(net, DeviceMesh.data_parallel(), batch_limit=16)
        try:
            rng = np.random.RandomState(0)
            xs = [rng.randn(2, 4).astype(np.float32) for _ in range(5)]
            obs = [pi.submit(x) for x in xs]
            outs = [o.get(timeout=30) for o in obs]
            for x, o in zip(xs, outs):
                want = np.asarray(net.output(x))
                np.testing.assert_allclose(o, want, rtol=1e-4, atol=1e-5)
        finally:
            pi.shutdown()

    def test_varied_request_sizes_bucket_padding(self, devices8):
        """Coalesced totals pad to power-of-two buckets (kills the
        per-size recompile, VERDICT r2 weak #5); results stay exact."""
        conf = (NeuralNetConfiguration.Builder().seed(2)
                .updater(updaters.Sgd(0.1)).list()
                .layer(DenseLayer(nOut=6, activation="tanh"))
                .layer(OutputLayer(nOut=2, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(3))
                .build())
        net = MultiLayerNetwork(conf).init()
        pi = ParallelInference(net, DeviceMesh.data_parallel(),
                               batch_limit=16, queue_timeout_ms=20.0)
        try:
            rng = np.random.RandomState(1)
            for sizes in ((1,), (3, 2), (5,), (7, 6), (1, 1, 1)):
                xs = [rng.randn(s, 3).astype(np.float32) for s in sizes]
                obs = [pi.submit(x) for x in xs]
                for x, o in zip(xs, obs):
                    got = o.get(timeout=30)
                    assert got.shape == (x.shape[0], 2)
                    np.testing.assert_allclose(
                        got, np.asarray(net.output(x)), rtol=1e-4, atol=1e-5)
        finally:
            pi.shutdown()


class TestShardedTransformer:
    def test_tp_sp_dp_train_step(self, devices8):
        """Full dp2 x tp2 x sp2 sharded transformer train step — the
        multi-chip path the driver dry-runs."""
        mesh = DeviceMesh.create(data=2, model=2, seq=2)
        cfg = tfm.TransformerConfig.tiny(dtype=jnp.float32,
                                         use_ring_attention=True, causal=True)
        with mesh:
            params = tfm.init_params(cfg, jax.random.PRNGKey(0))
            shardings = tfm.param_shardings(cfg, mesh)
            params = jax.tree_util.tree_map(jax.device_put, params, shardings,
                                            is_leaf=lambda x: isinstance(x, jax.Array))
            updater = updaters.Adam(1e-3)
            opt = tfm.init_opt_state(params, updater)
            step = tfm.make_train_step(cfg, updater, mesh)
            rng = np.random.RandomState(0)
            tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 32)), jnp.int32)
            targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 32)), jnp.int32)
            mask = jnp.ones((4, 32), jnp.float32)
            losses = []
            t_dev = jnp.asarray(0, jnp.int32)
            for t in range(3):
                params, opt, t_dev, loss = step(params, opt, t_dev,
                                                tokens, targets, mask)
                losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_sharded_forward_matches_unsharded(self, devices8):
        cfg = tfm.TransformerConfig.tiny(dtype=jnp.float32)
        mesh = DeviceMesh.create(data=2, model=2, seq=2)
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 16)), jnp.int32)
        ref = tfm.forward(params, tokens, cfg, mesh=None)
        with mesh:
            shardings = tfm.param_shardings(cfg, mesh)
            sp = jax.tree_util.tree_map(jax.device_put, params, shardings,
                                        is_leaf=lambda x: isinstance(x, jax.Array))
            out = jax.jit(lambda p, t: tfm.forward(p, t, cfg, mesh))(sp, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-4)
