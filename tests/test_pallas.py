"""Pallas platform-override tests (ref: the PlatformHelper dispatch tests
of libnd4j's mkldnn/cudnn helpers — same contract: the override must be
numerically interchangeable with the generic op, and unsupported shapes
must fall back). Kernels run via the Pallas interpreter on the CPU suite;
the same code compiles for TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from deeplearning4j_tpu.ops import pallas_kernels as pk
from deeplearning4j_tpu.ops import registry


@pytest.fixture
def overrides():
    pk.install_platform_overrides(interpret=True)
    yield
    pk.uninstall_platform_overrides()


class TestLayerNormKernel:
    def test_matches_generic(self):
        rng = np.random.RandomState(0)
        x = rng.randn(16, 256).astype(np.float32) * 3 + 1
        g = rng.rand(256).astype(np.float32) + 0.5
        b = rng.randn(256).astype(np.float32)
        ln = pk.make_layer_norm_override(interpret=True)
        from deeplearning4j_tpu.ops import normalization as norm_ops
        got = np.asarray(ln(x, g, b))
        want = np.asarray(norm_ops.layer_norm(x, g, b))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_gradients_flow(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(8, 128).astype(np.float32))
        g = jnp.asarray(rng.rand(128).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(128).astype(np.float32))
        ln = pk.make_layer_norm_override(interpret=True)
        from deeplearning4j_tpu.ops import normalization as norm_ops

        def loss_pallas(x, g, b):
            return jnp.sum(jnp.square(ln(x, g, b)))

        def loss_generic(x, g, b):
            return jnp.sum(jnp.square(norm_ops.layer_norm(x, g, b)))

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, g, b)
        gg = jax.grad(loss_generic, argnums=(0, 1, 2))(x, g, b)
        for a, bb in zip(gp, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                       rtol=1e-4, atol=1e-4)

    def test_unsupported_shape_falls_back(self):
        rng = np.random.RandomState(2)
        ln = pk.make_layer_norm_override(interpret=True)
        # lane dim 100 is not a multiple of 128: must use generic path
        x = rng.randn(8, 100).astype(np.float32)
        g = np.ones(100, np.float32)
        b = np.zeros(100, np.float32)
        from deeplearning4j_tpu.ops import normalization as norm_ops
        np.testing.assert_allclose(np.asarray(ln(x, g, b)),
                                   np.asarray(norm_ops.layer_norm(x, g, b)),
                                   rtol=1e-5, atol=1e-5)


class TestSoftmaxKernel:
    def test_matches_jax(self):
        rng = np.random.RandomState(3)
        x = rng.randn(32, 128).astype(np.float32) * 5
        sm = pk.make_softmax_override(interpret=True)
        np.testing.assert_allclose(np.asarray(sm(x)),
                                   np.asarray(jax.nn.softmax(x, axis=-1)),
                                   rtol=1e-5, atol=1e-6)

    def test_gradient_matches(self):
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(8, 128).astype(np.float32))
        sm = pk.make_softmax_override(interpret=True)
        gp = jax.grad(lambda v: jnp.sum(sm(v) ** 2))(x)
        gg = jax.grad(lambda v: jnp.sum(jax.nn.softmax(v, -1) ** 2))(x)
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gg),
                                   rtol=1e-4, atol=1e-5)


class TestPlatformDispatch:
    def test_override_shadows_generic(self, overrides):
        rng = np.random.RandomState(5)
        x = rng.randn(8, 128).astype(np.float32)
        got = np.asarray(registry.exec_op("softmax", x))
        np.testing.assert_allclose(got, np.asarray(jax.nn.softmax(x, -1)),
                                   rtol=1e-5, atol=1e-6)
        # the override IS what the registry resolves
        assert registry.get("softmax").__name__ == "softmax"
        assert registry.get("softmax") is not registry._REGISTRY["softmax"]

    def test_uninstall_restores_generic(self):
        pk.install_platform_overrides(interpret=True)
        pk.uninstall_platform_overrides()
        assert registry.get("softmax") is registry._REGISTRY["softmax"]

    def test_samediff_graph_uses_override(self, overrides):
        """A SameDiff graph records registry ops by name — the platform
        override applies when the graph executes."""
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        rng = np.random.RandomState(6)
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(8, 128), dtype=np.float32)
        y = x.mul(2.0)
        out = sd._record("softmax", [y.name])
        xv = rng.randn(8, 128).astype(np.float32)
        got = np.asarray(sd.output({"x": xv}, [out.name])[out.name])
        np.testing.assert_allclose(got, np.asarray(jax.nn.softmax(xv * 2, -1)),
                                   rtol=1e-5, atol=1e-6)


class TestFlashAttentionKernel:
    """Pallas fused flash attention (VERDICT r4 #5): forward and custom
    backward must match exact einsum attention."""

    def _qkv(self, B=2, T=256, H=2, D=64, dtype=jnp.float32, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32),
                                 dtype)
        return mk(), mk(), mk()

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_exact(self, causal):
        from deeplearning4j_tpu.ops import attention as attn_ops
        from deeplearning4j_tpu.ops.pallas_kernels import \
            make_flash_attention_override
        q, k, v = self._qkv()
        fa = make_flash_attention_override(interpret=True, bq=128, bk=128)
        got = np.asarray(fa(q, k, v, is_causal=causal))
        want = np.asarray(attn_ops.dot_product_attention(
            q, k, v, is_causal=causal))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_exact(self, causal):
        from deeplearning4j_tpu.ops import attention as attn_ops
        from deeplearning4j_tpu.ops.pallas_kernels import \
            make_flash_attention_override
        q, k, v = self._qkv(T=128)
        fa = make_flash_attention_override(interpret=True, bq=128, bk=128)

        def loss_fa(q, k, v):
            return jnp.sum(jnp.sin(fa(q, k, v, is_causal=causal)))

        def loss_exact(q, k, v):
            return jnp.sum(jnp.sin(attn_ops.dot_product_attention(
                q, k, v, is_causal=causal)))

        g1 = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    def test_masked_and_odd_shapes_fall_back(self):
        from deeplearning4j_tpu.ops.pallas_kernels import \
            make_flash_attention_override
        from deeplearning4j_tpu.ops import attention as attn_ops
        fa = make_flash_attention_override(interpret=True, bq=128, bk=128)
        rng = np.random.RandomState(1)
        # odd T (not block-divisible) and a mask both route to the scan path
        q = jnp.asarray(rng.randn(1, 100, 2, 64), jnp.float32)
        mask = jnp.ones((1, 1, 100, 100))
        got = np.asarray(fa(q, q, q, mask=mask))
        want = np.asarray(attn_ops.dot_product_attention(q, q, q, mask=mask))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_dispatch_through_flash_attention_entry(self):
        """attention.flash_attention routes through the installed override."""
        from deeplearning4j_tpu.ops import attention as attn_ops
        from deeplearning4j_tpu.ops import pallas_kernels as pk
        q, k, v = self._qkv(T=128)
        pk.install_platform_overrides(interpret=True)
        try:
            got = np.asarray(attn_ops.flash_attention(q, k, v))
        finally:
            pk.uninstall_platform_overrides()
        want = np.asarray(attn_ops.dot_product_attention(q, k, v))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
