"""Lifecycle loop tests (ISSUE 20): capture, gate, driver state machine,
registry canary routing, and THE chaos-storm pin — a seeded storm
(trainer SIGKILLed mid-roll + one bad candidate + one genuine SLO
regression during canary) that must end with the registry serving the
last good version, the driver resumed from its checkpointed state, zero
dropped requests, a bit-identical rollback, and zero steady-state
recompiles with trainer and registry sharing one mesh.
"""

import json
import os
import threading
import time
import warnings

import numpy as np
import pytest

from deeplearning4j_tpu.analysis.lifecycle import lint_lifecycle
from deeplearning4j_tpu.faults import FaultPlan, ServingLoad
from deeplearning4j_tpu.lifecycle import (EvalGate, GatePolicy,
                                          LifecycleDriver, TrafficCapture,
                                          TrainerKilledError,
                                          spawn_trainer_process)
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.serving.registry import (CanaryInProgressError,
                                                 ModelRegistry,
                                                 RollbackTargetGoneError)
from deeplearning4j_tpu.train import updaters
from deeplearning4j_tpu.train.resilience import DriverStateStore

NIN, NOUT = 4, 3
W0 = np.random.RandomState(0).randn(NIN, NOUT).astype(np.float32)


def linear_model(delta: float):
    """Deterministic candidate: x @ (W0 + delta) — bit-identical math
    is assertable across promote/rollback."""
    W = (W0 + np.float32(delta)).astype(np.float32)
    return lambda x: np.asarray(x, np.float32) @ W


def feats(rows, seed=0):
    return np.random.RandomState(seed).randn(rows, NIN).astype(np.float32)


def quiet_registry(**kw):
    kw.setdefault("batch_limit", 8)
    kw.setdefault("coalesce_ms", 0.5)
    return ModelRegistry(**kw)


# --------------------------------------------------------------- capture
class TestTrafficCapture:
    def test_sampling_is_deterministic_and_replayable(self, tmp_path):
        path = str(tmp_path / "cap.jsonl")
        cap = TrafficCapture(path, sample_rate=0.5)
        for i in range(10):
            cap.record(feats(2, seed=i), deadline=1.5)
        # credit accumulator: exactly round(10 * 0.5) records
        assert cap.captured == 5
        recs = TrafficCapture.load(path)
        assert len(recs) == 5
        assert all(r["rows"] == 2 and r["deadline"] == 1.5 for r in recs)
        load = TrafficCapture.to_serving_load(path)
        assert len(load) == 5
        assert [s.rows for s in load.specs] == [2] * 5
        ev = TrafficCapture.eval_features(path)
        assert ev.shape == (10, NIN)

    def test_truncated_tail_loads_cleanly(self, tmp_path):
        # flight-recorder style: a crash mid-append must not poison the
        # eval set the capture left behind
        path = str(tmp_path / "cap.jsonl")
        cap = TrafficCapture(path)
        cap.record(feats(2, seed=0))
        cap.record(feats(3, seed=1))
        with open(path, "a") as f:
            f.write('{"at": 0.5, "rows": 4, "deadl')   # torn record
        recs = TrafficCapture.load(path)
        assert [r["rows"] for r in recs] == [2, 3]
        assert TrafficCapture.eval_features(path).shape == (5, NIN)
        assert len(TrafficCapture.to_serving_load(path)) == 2

    def test_capture_failure_never_raises(self, tmp_path):
        cap = TrafficCapture(str(tmp_path / "no" / "such" / "dir" / "c.jl"))
        assert cap.record(feats(2)) is False
        assert cap.dropped == 1

    def test_max_records_bound(self, tmp_path):
        path = str(tmp_path / "cap.jsonl")
        cap = TrafficCapture(path, max_records=3)
        for i in range(6):
            cap.record(feats(1, seed=i))
        assert cap.captured == 3 and cap.dropped == 3
        assert len(TrafficCapture.load(path)) == 3

    def test_server_capture_hook(self, tmp_path):
        path = str(tmp_path / "cap.jsonl")
        cap = TrafficCapture(path)
        with quiet_registry(capture=cap) as reg:
            reg.load("m", linear_model(0.0), shapes=[(NIN,)])
            reg.output("m", feats(4))
            reg.output("m", feats(2))
        assert cap.captured == 2
        assert [r["rows"] for r in TrafficCapture.load(path)] == [4, 2]


# ------------------------------------------------------------------ gate
class TestEvalGate:
    def test_pass_and_parity_rejection(self):
        gate = EvalGate(GatePolicy(parity_bound=0.05))
        x = feats(16)
        ok = gate.evaluate(linear_model(1e-4), linear_model(0.0), x)
        assert ok and ok.reason is None
        bad = gate.evaluate(linear_model(5.0), linear_model(0.0), x)
        assert not bad and bad.reason == "parity_violation"
        assert bad.to_dict()["detail"]["parity_rel"] > 0.05

    def test_nan_candidate_rejected(self):
        gate = EvalGate()
        verdict = gate.evaluate(lambda x: np.full((len(x), NOUT), np.nan),
                                linear_model(0.0), feats(8))
        assert not verdict
        assert verdict.reason == "non_finite_outputs"
        assert verdict.detail["non_finite_values"] == 8 * NOUT

    def test_scorecard_regression_with_labels(self):
        x = feats(16)
        y = x @ W0     # ground truth IS the incumbent's function
        gate = EvalGate(GatePolicy(max_regression=0.05))
        good = gate.evaluate(linear_model(1e-4), linear_model(0.0), x, y)
        assert good
        bad = gate.evaluate(linear_model(1.0), linear_model(0.0), x, y)
        assert not bad and bad.reason == "scorecard_regression"
        assert bad.candidate_score > bad.incumbent_score

    def test_empty_eval_fails_closed(self):
        verdict = EvalGate().evaluate(linear_model(0.0), None, None)
        assert not verdict and verdict.reason == "insufficient_eval"


# ----------------------------------------------------------- state store
class TestDriverStateStore:
    def test_roundtrip_atomic(self, tmp_path):
        store = DriverStateStore(str(tmp_path))
        state = {"round": 3, "phase": "observe", "quarantined": []}
        store.save(state)
        assert DriverStateStore(str(tmp_path)).load() == state

    def test_corrupt_state_quarantined_not_trusted(self, tmp_path):
        store = DriverStateStore(str(tmp_path))
        store.save({"round": 1})
        with open(store.path) as f:
            doc = json.load(f)
        doc["state"]["round"] = 99          # tampered: checksum now wrong
        with open(store.path, "w") as f:
            json.dump(doc, f)
        assert store.load() is None
        assert os.path.exists(os.path.join(
            str(tmp_path), "quarantine_" + DriverStateStore.FILENAME))
        # and a fresh store starts clean, not from garbage
        assert store.load() is None


# -------------------------------------------------------- registry canary
class TestRegistryCanary:
    def test_fraction_is_deterministic(self):
        with quiet_registry() as reg:
            v1 = reg.load("m", linear_model(0.0), shapes=[(NIN,)])
            v2 = reg.load("m", linear_model(0.1), shapes=[(NIN,)])
            reg.begin_canary("m", v2, fraction=0.25)
            handles = [reg.submit("m", feats(2, seed=i)) for i in range(40)]
            for h in handles:
                h.get(10)
            on_canary = sum(1 for h in handles
                            if h.server == f"m:v{v2}")
            # credit accumulator: EXACTLY round(40 * 0.25), no noise
            assert on_canary == 10
            assert sum(1 for h in handles
                       if h.server == f"m:v{v1}") == 30
            # pinned submits never count against the accumulator
            assert reg.submit("m", feats(2), version=v1).get(10) is not None
            assert reg.canary("m") == {"version": v2, "fraction": 0.25}

    def test_roll_refused_while_canary_observing(self):
        # the driver leans on this: two interleaved observation windows
        # would make neither attributable
        with quiet_registry() as reg:
            reg.load("m", linear_model(0.0), shapes=[(NIN,)])
            v2 = reg.load("m", linear_model(0.1), shapes=[(NIN,)])
            v3 = reg.load("m", linear_model(0.2), shapes=[(NIN,)])
            reg.begin_canary("m", v2, fraction=0.5)
            with pytest.raises(CanaryInProgressError) as ei:
                reg.roll("m", v3)
            assert ei.value.canary == v2 and ei.value.target == v3
            with pytest.raises(CanaryInProgressError):
                reg.begin_canary("m", v3, fraction=0.5)
            # roll TO the canary version IS the promote, and clears it
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                reg.roll("m", v2)
            assert reg.active_version("m") == v2
            assert reg.canary("m") is None
            # with the canary gone, other rolls work again
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                reg.roll("m", v3)
            assert reg.active_version("m") == v3

    def test_promote_and_abort(self):
        with quiet_registry() as reg:
            reg.load("m", linear_model(0.0), shapes=[(NIN,)])
            v2 = reg.load("m", linear_model(0.1), shapes=[(NIN,)])
            reg.begin_canary("m", v2, fraction=0.5)
            assert reg.abort_canary("m") == v2
            assert reg.canary("m") is None
            assert reg.abort_canary("m") is None      # idempotent
            # the aborted version stays loaded and warmed
            reg.begin_canary("m", v2, fraction=0.5)
            assert reg.promote_canary("m") == v2
            assert reg.active_version("m") == v2
            assert reg.canary("m") is None

    def test_rollback_aborts_canary(self):
        with quiet_registry() as reg:
            reg.load("m", linear_model(0.0), shapes=[(NIN,)])
            v2 = reg.load("m", linear_model(0.1), shapes=[(NIN,)])
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                reg.roll("m", v2)
            v3 = reg.load("m", linear_model(0.2), shapes=[(NIN,)])
            reg.begin_canary("m", v3, fraction=0.5)
            assert reg.rollback("m") == 1
            assert reg.canary("m") is None

    def test_retire_refuses_observing_canary(self):
        with quiet_registry() as reg:
            reg.load("m", linear_model(0.0), shapes=[(NIN,)])
            v2 = reg.load("m", linear_model(0.1), shapes=[(NIN,)])
            reg.begin_canary("m", v2, fraction=0.5)
            with pytest.raises(ValueError, match="observing canary"):
                reg.retire("m", v2, timeout=1.0)

    def test_rollback_after_eviction_structured_error(self):
        # the driver leans on this: rollback() when the pre-roll
        # incumbent was retired must be a structured error, not KeyError
        with quiet_registry() as reg:
            v1 = reg.load("m", linear_model(0.0), shapes=[(NIN,)])
            v2 = reg.load("m", linear_model(0.1), shapes=[(NIN,)])
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                reg.roll("m", v2)
            reg.retire("m", v1, timeout=5.0)
            with pytest.raises(RollbackTargetGoneError) as ei:
                reg.rollback("m")
            assert not isinstance(ei.value, KeyError)
            assert isinstance(ei.value, ValueError)
            assert ei.value.model == "m" and ei.value.version == v1
            assert "no previous" in str(ei.value)

    def test_hints_and_models_carry_canary(self):
        with quiet_registry() as reg:
            reg.load("m", linear_model(0.0), shapes=[(NIN,)])
            v2 = reg.load("m", linear_model(0.1), shapes=[(NIN,)])
            assert reg.models()["m"]["canary"] is None
            reg.begin_canary("m", v2, fraction=0.2)
            m = reg.models()["m"]
            assert m["canary"] == v2 and m["canary_fraction"] == 0.2
            hints = reg.load_hints()["models"]["m"]
            assert hints["canary"]["version"] == v2
            assert hints["canary"]["fraction"] == 0.2
            assert "shed_rate" in hints["canary"]


# -------------------------------------------------------------- SLO layer
class TestBurnOver:
    def test_burn_over_does_not_perturb_the_ring(self):
        from deeplearning4j_tpu.profiler.slo import SLOEngine, SLOSpec
        from deeplearning4j_tpu.profiler import metrics as _metrics
        reg = _metrics.MetricsRegistry()
        req = reg.counter("dl4j_serving_requests_total", "t",
                          labelnames=("outcome",))
        spec = SLOSpec("serve", shed_rate=0.1, windows=(10.0, 100.0))
        t = [0.0]
        eng = SLOEngine([spec], registry=reg, clock=lambda: t[0])
        req.labels(outcome="completed").inc(100)
        eng.evaluate()
        n = len(eng._samples)
        t[0] = 30.0
        req.labels(outcome="shed_overload").inc(50)
        burns = eng.burn_over(20.0)
        # delta vs the 30s-old reference: 50 shed of 50 new -> 1.0/0.1
        assert burns["serve"] == pytest.approx(10.0)
        assert len(eng._samples) == n       # no sample appended


# ------------------------------------------------------------------ lints
class TestLifecycleLints:
    def test_w113_window_shorter_than_fast(self):
        rep = lint_lifecycle(observation_window=5.0, canary_fraction=0.2,
                             slo_windows=(60.0, 600.0))
        assert [d.code for d in rep.diagnostics] == ["DL4J-W113"]

    def test_w114_fraction_below_resolution(self):
        rep = lint_lifecycle(observation_window=120.0, canary_fraction=0.01,
                             slo_windows=(60.0, 600.0),
                             requests_per_tick=50)
        assert [d.code for d in rep.diagnostics] == ["DL4J-W114"]

    def test_w114_bucket_underfill(self):
        rep = lint_lifecycle(observation_window=120.0, canary_fraction=0.1,
                             requests_per_tick=40, buckets=[8, 16, 32])
        assert [d.code for d in rep.diagnostics] == ["DL4J-W114"]
        assert "bucket" in rep.diagnostics[0].message

    def test_clean_plan(self):
        rep = lint_lifecycle(observation_window=120.0, canary_fraction=0.25,
                             slo_windows=(60.0, 600.0),
                             requests_per_tick=100, buckets=[8, 16])
        assert rep.diagnostics == []

    def test_cli(self, capsys):
        from deeplearning4j_tpu.lifecycle.__main__ import main
        rc = main(["--observation-window", "5", "--canary-fraction", "0.2",
                   "--slo-windows", "60,600"])
        assert rc == 1
        assert "DL4J-W113" in capsys.readouterr().out
        rc = main(["--observation-window", "120",
                   "--canary-fraction", "0.25"])
        assert rc == 0


# ----------------------------------------------------------------- driver
def make_trainer():
    def trainer(r):
        return linear_model(0.001 * r)
    return trainer


class TestLifecycleDriver:
    def test_happy_path_promotes_each_round(self, tmp_path):
        with quiet_registry() as reg:
            drv = LifecycleDriver(reg, "m", make_trainer(),
                                  str(tmp_path / "state"),
                                  eval_x=feats(16), shapes=[(NIN,)],
                                  observe_ticks=1, confirm_ticks=1)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                summary = drv.run(3)
            assert summary["promotions"] == 3
            assert summary["rollbacks"] == 0
            assert summary["quarantined"] == []
            assert reg.active_version("m") == 3
            assert drv.incumbent_version == 3
            # driver resumable state is idle/clean
            st = DriverStateStore(str(tmp_path / "state")).load()
            assert st["phase"] == "idle" and st["in_round"] is None

    def test_bad_candidate_quarantined_never_loaded(self, tmp_path):
        plan = FaultPlan(bad_candidate_at={2: "nan"})
        with quiet_registry() as reg:
            drv = LifecycleDriver(reg, "m", make_trainer(),
                                  str(tmp_path / "state"),
                                  eval_x=feats(16), shapes=[(NIN,)],
                                  observe_ticks=1, confirm_ticks=1,
                                  faults=plan)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                summary = drv.run(3)
        assert summary["promotions"] == 2
        q = summary["quarantined"]
        assert len(q) == 1 and q[0]["reason"] == "gate:non_finite_outputs"
        assert q[0]["version"] is None      # NEVER loaded
        # versions 1 and 2 exist; the poisoned round produced none
        assert reg.models()["m"]["versions"].keys() == {1, 2}

    def test_regressed_candidate_quarantined(self, tmp_path):
        plan = FaultPlan(bad_candidate_at={2: "regressed"})
        with quiet_registry() as reg:
            drv = LifecycleDriver(reg, "m", make_trainer(),
                                  str(tmp_path / "state"),
                                  eval_x=feats(16), shapes=[(NIN,)],
                                  gate=EvalGate(GatePolicy(
                                      parity_bound=0.05)),
                                  observe_ticks=1, confirm_ticks=1,
                                  faults=plan)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                summary = drv.run(2)
        assert [q["reason"] for q in summary["quarantined"]] \
            == ["gate:parity_violation"]

    def test_trainer_death_mid_roll_then_resume(self, tmp_path):
        plan = FaultPlan(trainer_death_at_roll=1)
        proc = spawn_trainer_process()
        state_dir = str(tmp_path / "state")
        with quiet_registry() as reg:
            drv = LifecycleDriver(reg, "m", make_trainer(), state_dir,
                                  eval_x=feats(16), shapes=[(NIN,)],
                                  observe_ticks=1, confirm_ticks=1,
                                  faults=plan, trainer_process=proc)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with pytest.raises(TrainerKilledError):
                    drv.run(2)
            # the trainer subprocess is DEAD (SIGKILL)
            assert proc.poll() is not None and proc.returncode == -9
            # registry is consistent: incumbent serving, canary live or
            # cleanly abortable, v2 loaded
            assert reg.active_version("m") == 1
            np.testing.assert_array_equal(
                reg.output("m", feats(4)),
                linear_model(0.001)(feats(4)))
            # a NEW driver over the same state_dir resumes the round
            drv2 = LifecycleDriver(reg, "m", make_trainer(), state_dir,
                                   eval_x=feats(16), shapes=[(NIN,)],
                                   observe_ticks=1, confirm_ticks=1,
                                   faults=plan)
            assert drv2.resumed
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                summary = drv2.run(2)
            assert summary["rounds"] == 2
            assert reg.active_version("m") == 2
            assert reg.canary("m") is None
            # the interrupted candidate was NOT retrained or reloaded
            assert reg.models()["m"]["versions"].keys() == {1, 2}


# ------------------------------------------------------------- THE storm
def storm_net():
    conf = (NeuralNetConfiguration.Builder().seed(7)
            .updater(updaters.Sgd(0.05)).list()
            .layer(DenseLayer(nOut=8, activation="relu"))
            .layer(OutputLayer(nOut=NOUT, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(NIN))
            .build())
    return MultiLayerNetwork(conf).init()


@pytest.mark.chaos
class TestChaosStorm:
    def test_train_gate_roll_rollback_storm(self, tmp_path):
        """THE pin (acceptance criteria): seed 23 fires all three chaos
        kinds — trainer SIGKILLed mid-roll (roll 2), one NaN candidate
        (round 3), one SLO regression during canary (roll 4) — across 5
        rounds under live traffic, with a REAL trainer fitting on the
        same mesh the registry serves from."""
        plan = FaultPlan.seeded_lifecycle(seed=23, rounds=5, n_bad=1,
                                          trainer_death=True,
                                          slo_regression=True)
        assert plan.trainer_death_at_roll == 2
        assert plan.bad_candidate_at == {3: "nan"}
        assert plan.slo_regression_during_canary == 4

        from deeplearning4j_tpu.analysis.churn import get_churn_detector
        det = get_churn_detector()
        net = storm_net()
        fit_x = feats(8, seed=3)
        fit_y = np.eye(NOUT, dtype=np.float32)[
            np.random.RandomState(4).randint(NOUT, size=8)]

        def trainer(r):
            # the REAL trainer: fit on the shared mesh every round
            net.fit(fit_x, fit_y)
            return linear_model(0.001 * r)

        # warm the compiled fit path once, then pin its signature count:
        # rounds must reuse it (zero steady-state trainer recompiles)
        trainer(0)
        fit_sigs = det.signature_count("MultiLayerNetwork.fit", owner=net)

        proc = spawn_trainer_process()
        state_dir = str(tmp_path / "state")
        stop = threading.Event()
        handles, submit_errors = [], []

        reg = quiet_registry()
        try:
            from deeplearning4j_tpu.serving.registry import \
                ModelNotFoundError

            def traffic():
                i = 0
                while not stop.is_set():
                    try:
                        if reg.active_version("m") is not None:
                            handles.append(
                                reg.submit("m", feats(2, seed=i)))
                    except ModelNotFoundError:
                        pass            # nothing loaded yet
                    except Exception as e:   # admission shed = outcome
                        submit_errors.append(e)
                    i += 1
                    time.sleep(0.02)

            t = threading.Thread(target=traffic, daemon=True)
            t.start()

            def driver(faults):
                return LifecycleDriver(
                    reg, "m", trainer, state_dir, eval_x=feats(16),
                    shapes=[(NIN,)], canary_fraction=0.25,
                    observe_ticks=2, confirm_ticks=1,
                    tick_interval=0.05, faults=faults,
                    trainer_process=proc)

            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                drv = driver(plan)
                with pytest.raises(TrainerKilledError):
                    drv.run(5)
                # mid-roll SIGKILL: trainer dead, registry consistent
                assert proc.poll() is not None and proc.returncode == -9
                assert reg.active_version("m") == 2

                drv2 = driver(plan)
                assert drv2.resumed     # resumed from checkpointed state
                drv2.run(4)             # finish the interrupted round 4
                assert reg.active_version("m") == 3
                # bit-identical pre-roll incumbent evidence
                probe = feats(8, seed=99)
                pre_roll = np.asarray(reg.output("m", probe))

                drv2.run(5)             # round 5: promote v4 -> SLO
                #                         regression -> auto-rollback
            stop.set()
            t.join(5.0)

            # (1) registry serves the LAST GOOD version
            assert reg.active_version("m") == 3
            assert drv2.incumbent_version == 3
            assert drv2.rollbacks == 1
            assert [q["reason"] for q in drv2.quarantined] == \
                ["gate:non_finite_outputs", "slo_regression"]

            # (2) rollback is bit-identical to the pre-roll incumbent
            post_roll = np.asarray(reg.output("m", probe))
            np.testing.assert_array_equal(pre_roll, post_roll)
            np.testing.assert_array_equal(
                post_roll, linear_model(0.004)(probe))

            # (3) zero dropped requests: every admitted request resolved
            # exactly once; every rejection was a structured outcome
            assert handles, "traffic thread never submitted"
            for h in handles:
                try:
                    h.get(15.0)
                except Exception:
                    pass                # structured outcome, not a drop
                assert h.resolutions == 1
            from deeplearning4j_tpu.serving import ServingError
            assert all(isinstance(e, ServingError)
                       for e in submit_errors)

            # (4) zero steady-state recompiles, trainer and registry on
            # one mesh: the fit signature set never grew after warmup,
            # and no version's server compiled past its own warmup
            assert det.signature_count("MultiLayerNetwork.fit",
                                       owner=net) == fit_sigs
            for v in reg.models()["m"]["versions"]:
                assert reg.server("m", v).recompiles_after_warmup() == 0
            assert not det.diagnostics_for(net)

            # (5) driver state machine ends clean and idle
            st = DriverStateStore(state_dir).load()
            assert st["phase"] == "idle" and st["in_round"] is None
            assert st["round"] == 5
        finally:
            stop.set()
            if proc.poll() is None:
                proc.kill()
            reg.close()

    def test_capture_doubles_as_chaos_input(self, tmp_path):
        """Captured live traffic replays as a deterministic ServingLoad
        against a fresh registry — the capture IS the chaos input."""
        path = str(tmp_path / "cap.jsonl")
        cap = TrafficCapture(path, sample_rate=1.0)
        with quiet_registry(capture=cap) as reg:
            reg.load("m", linear_model(0.0), shapes=[(NIN,)])
            load = ServingLoad.seeded(11, mix="steady", n=30, rps=400.0,
                                      max_rows=4)
            outcomes = load.replay(
                lambda x, deadline=None:
                reg.submit("m", x, deadline=deadline), (NIN,))
            for _spec, out in outcomes:
                assert not isinstance(out, Exception)
                out.get(10.0)
        assert cap.captured == 30
        replay = TrafficCapture.to_serving_load(path)
        assert [s.rows for s in replay.specs] == \
            [s.rows for s in load.specs]
        with quiet_registry() as reg2:
            reg2.load("m", linear_model(0.5), shapes=[(NIN,)])
            outcomes = replay.replay(
                lambda x, deadline=None:
                reg2.submit("m", x, deadline=deadline), (NIN,),
                time_scale=0.5)
            for _spec, out in outcomes:
                assert not isinstance(out, Exception)
                assert out.get(10.0) is not None
                assert out.resolutions == 1
