"""Graph engine tests (SameDiff equivalent).

Reference test-strategy parity (SURVEY.md §4): eager-vs-graph equality,
numeric gradient checks, serialization round-trips, training convergence.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.train import updaters


class TestGraphBasics:
    def test_forward_matches_eager(self):
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 4))
        w = sd.var("w", np.ones((4, 3), np.float32))
        b = sd.var("b", np.zeros((3,), np.float32))
        out = sd.nn.softmax(x.mmul(w).add(b), name="out")
        data = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        res = sd.output({"x": data}, ["out"])["out"]
        want = jax.nn.softmax(data @ np.ones((4, 3), np.float32))
        np.testing.assert_allclose(res, want, rtol=1e-5)

    def test_fluent_arith(self):
        sd = SameDiff.create()
        a = sd.var("a", np.asarray([1.0, 2.0]))
        b = sd.var("b", np.asarray([3.0, 4.0]))
        c = (a + b) * 2.0 - 1.0
        np.testing.assert_allclose(c.eval(), [7.0, 11.0])

    def test_reductions_and_shapes(self):
        sd = SameDiff.create()
        x = sd.var("x", np.arange(6, dtype=np.float32).reshape(2, 3))
        s = x.sum(1)
        m = x.mean()
        r = x.reshape(3, 2).transpose(1, 0)
        np.testing.assert_allclose(s.eval(), [3.0, 12.0])
        assert float(m.eval()) == 2.5
        assert r.eval().shape == (2, 3)

    def test_duplicate_names_uniquified(self):
        sd = SameDiff.create()
        a = sd.var("a", np.ones(2))
        x1 = a.add(1.0)
        x2 = a.add(1.0)
        assert x1.name != x2.name

    def test_variable_update_invalidate(self):
        sd = SameDiff.create()
        a = sd.var("a", np.asarray(1.0))
        out = a.mul(2.0)
        assert float(out.eval()) == 2.0
        sd.getVariable("a").setArray(np.asarray(5.0))
        assert float(out.eval()) == 10.0


class TestGradients:
    def test_gradcheck_mlp(self):
        """Finite-difference through a small graph in fp64 (SURVEY §4)."""
        with jax.experimental.enable_x64():
            sd = SameDiff.create()
            rng = np.random.RandomState(1)
            x_data = rng.randn(4, 3)
            y_data = np.eye(2)[rng.randint(0, 2, 4)]
            x = sd.placeHolder("x", shape=(None, 3), dtype=jnp.float64)
            labels = sd.placeHolder("labels", shape=(None, 2), dtype=jnp.float64)
            w1 = sd.var("w1", rng.randn(3, 5) * 0.5)
            b1 = sd.var("b1", np.zeros(5))
            w2 = sd.var("w2", rng.randn(5, 2) * 0.5)
            h = sd.nn.tanh(x.mmul(w1).add(b1))
            logits = h.mmul(w2)
            loss = sd.loss.softmaxCrossEntropy(labels, logits, name="loss")
            sd.setLossVariables("loss")
            phs = {"x": x_data, "labels": y_data}
            grads = sd.calculateGradients(phs, ["w1", "w2", "b1"])

            def loss_at(vname, arr):
                old = sd._variables[vname]
                sd._variables = dict(sd._variables, **{vname: arr})
                v = float(sd.output(phs, ["loss"])["loss"])
                sd._variables = dict(sd._variables, **{vname: old})
                return v

            eps = 1e-6
            for vname in ["w1", "b1", "w2"]:
                arr = np.asarray(sd._variables[vname])
                flat_g = np.asarray(grads[vname]).ravel()
                for idx in range(0, arr.size, max(1, arr.size // 5)):
                    pert = arr.copy().ravel()
                    pert[idx] += eps
                    fp = loss_at(vname, jnp.asarray(pert.reshape(arr.shape)))
                    pert[idx] -= 2 * eps
                    fm = loss_at(vname, jnp.asarray(pert.reshape(arr.shape)))
                    fd = (fp - fm) / (2 * eps)
                    np.testing.assert_allclose(flat_g[idx], fd, rtol=1e-4, atol=1e-7)


class TestTraining:
    def _xor_sd(self, seed=42):
        sd = SameDiff.create()
        rng = np.random.RandomState(seed)
        x = sd.placeHolder("x", shape=(None, 2))
        labels = sd.placeHolder("labels", shape=(None, 2))
        w1 = sd.var("w1", rng.randn(2, 8).astype(np.float32))
        b1 = sd.var("b1", np.zeros(8, np.float32))
        w2 = sd.var("w2", rng.randn(8, 2).astype(np.float32))
        b2 = sd.var("b2", np.zeros(2, np.float32))
        h = sd.nn.tanh(x.mmul(w1).add(b1))
        logits = h.mmul(w2).add(b2).rename("logits")
        sd.loss.softmaxCrossEntropy(labels, logits, name="loss")
        sd.setLossVariables("loss")
        return sd

    XOR_X = np.asarray([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
    XOR_Y = np.asarray([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)

    def test_fit_xor_converges(self):
        sd = self._xor_sd()
        sd.setTrainingConfig(TrainingConfig(
            updater=updaters.Adam(0.05),
            data_set_feature_mapping=["x"], data_set_label_mapping=["labels"]))
        hist = sd.fit(data={"x": self.XOR_X, "labels": self.XOR_Y}, epochs=300)
        assert hist.loss_curve[-1] < 0.05, hist.loss_curve[-1]
        preds = sd.output({"x": self.XOR_X}, ["logits"])["logits"]
        assert (np.argmax(preds, 1) == np.argmax(self.XOR_Y, 1)).all()

    @pytest.mark.parametrize("updater_cls", [
        updaters.Sgd, updaters.Adam, updaters.AdamW, updaters.Nesterovs,
        updaters.RmsProp, updaters.AdaGrad, updaters.AdaMax,
        updaters.AMSGrad, updaters.Nadam])
    def test_every_updater_reduces_loss(self, updater_cls):
        sd = self._xor_sd()
        sd.setTrainingConfig(TrainingConfig(
            updater=updater_cls(0.02),
            data_set_feature_mapping=["x"], data_set_label_mapping=["labels"]))
        hist = sd.fit(data={"x": self.XOR_X, "labels": self.XOR_Y}, epochs=60)
        assert hist.loss_curve[-1] < hist.loss_curve[0]

    def test_adadelta_reduces_loss(self):
        sd = self._xor_sd()
        sd.setTrainingConfig(TrainingConfig(
            updater=updaters.AdaDelta(),
            data_set_feature_mapping=["x"], data_set_label_mapping=["labels"]))
        hist = sd.fit(data={"x": self.XOR_X, "labels": self.XOR_Y}, epochs=60)
        assert hist.loss_curve[-1] < hist.loss_curve[0]

    def test_l2_and_clipping(self):
        sd = self._xor_sd()
        sd.setTrainingConfig(TrainingConfig(
            updater=updaters.Sgd(0.1), l2=1e-3, clip_global_norm=1.0,
            data_set_feature_mapping=["x"], data_set_label_mapping=["labels"]))
        hist = sd.fit(data={"x": self.XOR_X, "labels": self.XOR_Y}, epochs=50)
        assert hist.loss_curve[-1] < hist.loss_curve[0]

    def test_tuple_batches_via_mapping(self):
        sd = self._xor_sd()
        sd.setTrainingConfig(TrainingConfig(
            updater=updaters.Adam(0.05),
            data_set_feature_mapping=["x"], data_set_label_mapping=["labels"]))
        batches = [(self.XOR_X, self.XOR_Y)] * 50
        hist = sd.fit(iterator=batches)
        assert hist.loss_curve[-1] < hist.loss_curve[0]


class TestControlFlow:
    def test_while_loop(self):
        sd = SameDiff.create()
        i0 = sd.constant(jnp.asarray(0.0), name="i0")
        acc0 = sd.constant(jnp.asarray(1.0), name="acc0")
        i_out, acc_out = sd.while_loop(
            lambda i, acc: i < 5,
            lambda i, acc: (i + 1, acc * 2),
            [i0, acc0])
        assert float(acc_out.eval()) == 32.0

    def test_while_loop_single_var(self):
        sd = SameDiff.create()
        i0 = sd.constant(jnp.asarray(0.0), name="j0")
        out = sd.while_loop(lambda i: i < 5, lambda i: (i + 1,), [i0])
        assert float(out.eval()) == 5.0

    def test_cond(self):
        sd = SameDiff.create()
        p = sd.constant(jnp.asarray(True), name="p")
        a = sd.constant(jnp.asarray(2.0), name="a")
        out = sd.cond(p, lambda v: v * 10, lambda v: v - 1, [a])
        assert float(out.eval()) == 20.0


class TestSerialization:
    def test_save_load_roundtrip(self, tmp_path):
        sd = TestTraining()._xor_sd()
        sd.setTrainingConfig(TrainingConfig(
            updater=updaters.Adam(0.05),
            data_set_feature_mapping=["x"], data_set_label_mapping=["labels"]))
        hist = sd.fit(data={"x": TestTraining.XOR_X, "labels": TestTraining.XOR_Y},
                      epochs=30)
        path = str(tmp_path / "model.sdz")
        sd.save(path)

        sd2 = SameDiff.load(path)
        # exact forward parity after round-trip
        out1 = sd.output({"x": TestTraining.XOR_X}, ["logits"])["logits"]
        out2 = sd2.output({"x": TestTraining.XOR_X}, ["logits"])["logits"]
        np.testing.assert_allclose(out1, out2, rtol=1e-6)
        # training resumes with updater state (exact-resume contract,
        # ref: ModelSerializer updater-state binary)
        h1 = sd.fit(data={"x": TestTraining.XOR_X, "labels": TestTraining.XOR_Y}, epochs=1)
        h2 = sd2.fit(data={"x": TestTraining.XOR_X, "labels": TestTraining.XOR_Y}, epochs=1)
        np.testing.assert_allclose(h1.loss_curve[-1], h2.loss_curve[-1], rtol=1e-5)

    def test_schedule_roundtrip(self):
        from deeplearning4j_tpu.train import schedules
        s = schedules.StepSchedule("iteration", 0.1, 0.5, 100)
        s2 = schedules.ISchedule.from_config(s.to_config())
        assert float(s2.valueAt(250)) == pytest.approx(0.025)

    def test_ramp_schedule_roundtrip(self):
        from deeplearning4j_tpu.train import schedules
        r = schedules.RampSchedule(schedules.FixedSchedule(1.0), 10)
        r2 = schedules.ISchedule.from_config(r.to_config())
        assert float(r2.valueAt(4)) == pytest.approx(0.5)

    def test_rng_nodes_roundtrip(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 4))
        d = sd.nn.dropout(x, 0.5, name="d")
        u = sd.random.uniform(0.0, 1.0, (3,), name="u")
        path = str(tmp_path / "rng.sdz")
        sd.save(path)
        sd2 = SameDiff.load(path)
        data = np.ones((2, 4), np.float32)
        # inference mode: dropout is identity
        out = sd2.output({"x": data}, ["d"])["d"]
        np.testing.assert_allclose(out, data)
        # train mode executes the rng path
        out_t = sd2.output({"x": data}, ["d"], train=True)["d"]
        assert out_t.shape == (2, 4)
        uv = sd2.output({}, ["u"])["u"]
        assert uv.shape == (3,) and (np.asarray(uv) >= 0).all()

    def test_cast_roundtrip(self, tmp_path):
        import jax.numpy as jnp
        sd = SameDiff.create()
        a = sd.var("a", np.asarray([1.5, 2.5], np.float32))
        c = a.castTo(jnp.int32).rename("c")
        path = str(tmp_path / "cast.sdz")
        sd.save(path)
        sd2 = SameDiff.load(path)
        out = sd2.output({}, ["c"])["c"]
        assert out.dtype == jnp.int32

    def test_grad_cache_invalidated_on_loss_change(self):
        sd = SameDiff.create()
        x = sd.var("x", np.asarray(3.0))
        a = x.mul(2.0).rename("lossA")   # dA/dx = 2
        b = x.mul(x).rename("lossB")     # dB/dx = 2x = 6
        sd.setLossVariables("lossA")
        g1 = sd.calculateGradients({}, ["x"])["x"]
        assert float(g1) == pytest.approx(2.0)
        sd.setLossVariables("lossB")
        g2 = sd.calculateGradients({}, ["x"])["x"]
        assert float(g2) == pytest.approx(6.0)


class TestSchedules:
    def test_values(self):
        from deeplearning4j_tpu.train import schedules
        assert float(schedules.ExponentialSchedule("iteration", 1.0, 0.9).valueAt(2)) == pytest.approx(0.81)
        assert float(schedules.PolySchedule("iteration", 1.0, 2.0, 100).valueAt(50)) == pytest.approx(0.25)
        assert float(schedules.InverseSchedule("iteration", 1.0, 1.0, 1.0).valueAt(1)) == pytest.approx(0.5)
        m = schedules.MapSchedule("iteration", {0: 0.1, 10: 0.01})
        assert float(m.valueAt(5)) == pytest.approx(0.1)
        assert float(m.valueAt(15)) == pytest.approx(0.01)
        r = schedules.RampSchedule(schedules.FixedSchedule(1.0), 10)
        assert float(r.valueAt(4)) == pytest.approx(0.5)


class TestReviewRegressions:
    def test_batchnorm_node_roundtrip(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 3))
        mean = sd.var("mean", np.asarray([1.0, 2.0, 3.0], np.float32))
        var = sd.var("var", np.ones(3, np.float32))
        gamma = sd.var("gamma", np.full(3, 2.0, np.float32))
        beta = sd.var("beta", np.zeros(3, np.float32))
        out = sd.nn.batchNorm(x, mean, var, gamma, beta, axis=1).rename("bn")
        data = np.asarray([[2.0, 2.0, 2.0]], np.float32)
        before = np.asarray(sd.output({"x": data}, ["bn"])["bn"])
        path = str(tmp_path / "bn.sdz")
        sd.save(path)
        after = np.asarray(SameDiff.load(path).output({"x": data}, ["bn"])["bn"])
        np.testing.assert_allclose(before, after, rtol=1e-6)

    def test_lstm_node_roundtrip(self, tmp_path):
        rng = np.random.RandomState(0)
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 2, 3))
        wi = sd.var("wi", rng.randn(3, 16).astype(np.float32) * 0.1)
        wh = sd.var("wh", rng.randn(4, 16).astype(np.float32) * 0.1)
        b = sd.var("b", np.zeros(16, np.float32))
        out = sd.rnn.lstmLayer(x, wi, wh, b).rename("h")
        data = rng.randn(5, 2, 3).astype(np.float32)
        before = np.asarray(sd.output({"x": data}, ["h"])["h"])
        path = str(tmp_path / "lstm.sdz")
        sd.save(path)
        after = np.asarray(SameDiff.load(path).output({"x": data}, ["h"])["h"])
        np.testing.assert_allclose(before, after, rtol=1e-6)
        assert after.shape == (5, 2, 4)

    def test_map_schedule_json_roundtrip(self):
        import json as _json
        from deeplearning4j_tpu.train import schedules
        m = schedules.MapSchedule("iteration", {0: 0.1, 10: 0.01})
        m2 = schedules.ISchedule.from_config(_json.loads(_json.dumps(m.to_config())))
        assert float(m2.valueAt(5)) == pytest.approx(0.1)
        assert float(m2.valueAt(15)) == pytest.approx(0.01)

    def test_grad_wrt_placeholder(self):
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(2,))
        w = sd.var("w", np.asarray([2.0, 3.0], np.float32))
        loss = (x * w).sum().rename("loss")
        sd.setLossVariables("loss")
        g = sd.calculateGradients({"x": np.ones(2, np.float32)}, ["x", "w"])
        np.testing.assert_allclose(g["x"], [2.0, 3.0])
        np.testing.assert_allclose(g["w"], [1.0, 1.0])

    def test_unique_never_collides_with_vars(self):
        sd = SameDiff.create()
        a = sd.var("a", np.ones(2, np.float32))
        sd.var("add_1", np.zeros(2, np.float32))
        o1 = a.add(1.0)
        o2 = a.add(1.0)
        o3 = a.add(1.0)
        names = {o1.name, o2.name, o3.name}
        assert "add_1" not in names and len(names) == 3
        assert sd.getVariable("add_1").var_type == "VARIABLE"

    def test_mean_squared_error_saves(self, tmp_path):
        sd = SameDiff.create()
        a = sd.var("a", np.ones(3, np.float32))
        b = sd.var("b", np.zeros(3, np.float32))
        sd.loss.meanSquaredError(a, b, name="l")
        sd.save(str(tmp_path / "m.sdz"))


class TestClosureNodeSerialization:
    """Round-trips for closure-backed nodes rebuilt via _FN_REBUILDERS
    (VERDICT r1 weak #5 / ADVICE r1 medium)."""

    def _roundtrip(self, sd, tmp_path, phs, out):
        before = np.asarray(sd.output(phs, [out])[out])
        path = str(tmp_path / "g.sdz")
        sd.save(path)
        after = np.asarray(SameDiff.load(path).output(phs, [out])[out])
        np.testing.assert_allclose(before, after, rtol=1e-6)
        return after

    def test_mha_masked_roundtrip(self, tmp_path):
        rng = np.random.RandomState(0)
        d, h = 8, 2
        sd = SameDiff.create()
        q = sd.placeHolder("q", shape=(None, 5, d))
        kv = sd.placeHolder("kv", shape=(None, 5, d))
        wq = sd.var("wq", rng.randn(d, d).astype(np.float32) * 0.1)
        wk = sd.var("wk", rng.randn(d, d).astype(np.float32) * 0.1)
        wv = sd.var("wv", rng.randn(d, d).astype(np.float32) * 0.1)
        wo = sd.var("wo", rng.randn(d, d).astype(np.float32) * 0.1)
        # mask broadcastable to [B, H, Tq, Tk]: block the last two keys
        mask = sd.constant(
            np.asarray([1, 1, 1, 0, 0], np.float32).reshape(1, 1, 1, 5), name="m")
        sd.nn.multiHeadDotProductAttention(q, kv, wq, wk, wv, wo, num_heads=h,
                                           mask=mask, name="att")
        phs = {"q": rng.randn(1, 5, d).astype(np.float32),
               "kv": rng.randn(1, 5, d).astype(np.float32)}
        self._roundtrip(sd, tmp_path, phs, "att")

    def test_mha_unmasked_roundtrip(self, tmp_path):
        rng = np.random.RandomState(1)
        d, h = 8, 2
        sd = SameDiff.create()
        q = sd.placeHolder("q", shape=(None, 4, d))
        wq = sd.var("wq", rng.randn(d, d).astype(np.float32) * 0.1)
        wk = sd.var("wk", rng.randn(d, d).astype(np.float32) * 0.1)
        wv = sd.var("wv", rng.randn(d, d).astype(np.float32) * 0.1)
        wo = sd.var("wo", rng.randn(d, d).astype(np.float32) * 0.1)
        sd.nn.multiHeadDotProductAttention(q, q, wq, wk, wv, wo, num_heads=h,
                                           name="att")
        phs = {"q": rng.randn(2, 4, d).astype(np.float32)}
        self._roundtrip(sd, tmp_path, phs, "att")

    def test_std_variance_roundtrip(self, tmp_path):
        rng = np.random.RandomState(2)
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 4))
        sd.math.std(x, 1, name="s")
        sd.math.variance(x, 0, name="v")
        data = rng.randn(3, 4).astype(np.float32)
        before_s = np.asarray(sd.output({"x": data}, ["s"])["s"])
        before_v = np.asarray(sd.output({"x": data}, ["v"])["v"])
        path = str(tmp_path / "sv.sdz")
        sd.save(path)
        sd2 = SameDiff.load(path)
        np.testing.assert_allclose(
            np.asarray(sd2.output({"x": data}, ["s"])["s"]), before_s, rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sd2.output({"x": data}, ["v"])["v"]), before_v, rtol=1e-6)
        np.testing.assert_allclose(before_s, np.std(data, axis=1, ddof=1), rtol=1e-5)

    def test_getitem_roundtrip(self, tmp_path):
        rng = np.random.RandomState(3)
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 6))
        x[1:3, ::2].rename("g")
        data = rng.randn(5, 6).astype(np.float32)
        after = self._roundtrip(sd, tmp_path, {"x": data}, "g")
        np.testing.assert_allclose(after, data[1:3, ::2], rtol=1e-6)

    def test_getitem_int_and_newaxis_roundtrip(self, tmp_path):
        rng = np.random.RandomState(4)
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 6))
        x[(0, None, Ellipsis)].rename("g")
        data = rng.randn(5, 6).astype(np.float32)
        after = self._roundtrip(sd, tmp_path, {"x": data}, "g")
        np.testing.assert_allclose(after, data[0, None, ...], rtol=1e-6)

    def test_while_loop_save_refused_with_reason(self, tmp_path):
        sd = SameDiff.create()
        i = sd.var("i", np.asarray(0.0, np.float32))
        sd.while_loop(lambda v: v < 5.0, lambda v: v + 1.0, [i])
        with pytest.raises(ValueError, match="not serializable"):
            sd.save(str(tmp_path / "wl.sdz"))


class TestSubgraphControlFlow:
    """while/cond with SameDiff-subgraph bodies serialize and round-trip
    (VERDICT r4 #10 — the reference FlatBuffers its Enter/Exit/Merge
    frames; here the bodies are nested SameDiff graphs)."""

    def _loop_graphs(self):
        cond = SameDiff.create()
        ci = cond.placeHolder("i", shape=(), dtype=np.int32)
        cond.placeHolder("a", shape=(2, 3), dtype=np.float32)
        ci.lt(5.0)                      # recorded: last output is the pred
        body = SameDiff.create()
        bi = body.placeHolder("i", shape=(), dtype=np.int32)
        ba = body.placeHolder("a", shape=(2, 3), dtype=np.float32)
        ni = bi.add(1)
        na = ba.mul(1.5)
        body.setOutputs(ni, na)
        return cond, body

    def test_subgraph_while_executes_and_roundtrips(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(2, 3), dtype=np.float32)
        i0 = sd.constant(np.int32(0), name="i0")
        outs = sd.while_loop(self._loop_graphs()[0], self._loop_graphs()[1],
                             [i0, x], name="loop")
        res_name = outs[1].name
        feeds = {"x": np.ones((2, 3), np.float32)}
        want = np.ones((2, 3)) * 1.5 ** 5
        got = sd.output(feeds, [res_name])[res_name]
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)

        p = str(tmp_path / "subwhile.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        got2 = sd2.output(feeds, [res_name])[res_name]
        np.testing.assert_allclose(np.asarray(got2), want, rtol=1e-5)

    def test_subgraph_cond_roundtrips(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(3,), dtype=np.float32)
        pred = sd.placeHolder("p", shape=(), dtype=np.bool_)
        tg = SameDiff.create()
        ta = tg.placeHolder("a", shape=(3,), dtype=np.float32)
        tg.setOutputs(ta.mul(2.0))
        fg = SameDiff.create()
        fa = fg.placeHolder("a", shape=(3,), dtype=np.float32)
        fg.setOutputs(fa.sub(1.0))
        out = sd.cond(pred, tg, fg, [x], name="branch")
        feeds = {"x": np.asarray([1., 2., 3.], np.float32)}
        got_t = sd.output({**feeds, "p": np.bool_(True)}, [out.name])[out.name]
        got_f = sd.output({**feeds, "p": np.bool_(False)}, [out.name])[out.name]
        np.testing.assert_allclose(np.asarray(got_t), [2., 4., 6.])
        np.testing.assert_allclose(np.asarray(got_f), [0., 1., 2.])

        p = str(tmp_path / "subcond.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        got2 = sd2.output({**feeds, "p": np.bool_(True)}, [out.name])[out.name]
        np.testing.assert_allclose(np.asarray(got2), [2., 4., 6.])

    def test_invoke_subgraph_is_differentiable(self):
        sub = SameDiff.create()
        a = sub.placeHolder("a", shape=(2, 2), dtype=np.float32)
        sub.setOutputs(a.mul(a))
        sd = SameDiff.create()
        w = sd.var("w", np.ones((2, 2), np.float32) * 3.0)
        y = sd.invoke_subgraph(sub, [w], name="sq")
        sd.setLossVariables(y.name)
        g = sd.calculateGradients({}, ["w"])["w"]
        np.testing.assert_allclose(np.asarray(g), np.full((2, 2), 6.0))

    def test_raw_callable_while_still_rejects_save(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(2,), dtype=np.float32)
        sd.while_loop(lambda i, a: i < 3,
                      lambda i, a: (i + 1, a * 2.0),
                      [sd.constant(np.int32(0)), x], name="rawloop")
        with pytest.raises(ValueError, match="SameDiff subgraphs"):
            sd.save(str(tmp_path / "raw.sdz"))

    def test_rng_inside_subgraph_body_stays_live(self, tmp_path):
        """Dropout inside an invoke_subgraph body must act as dropout in
        training mode (key/train thread through the subgraph call)."""
        sub = SameDiff.create()
        a = sub.placeHolder("a", shape=(64, 64), dtype=np.float32)
        d = sub.nn.dropout(a, 0.5)
        sub.setOutputs(d)

        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(64, 64), dtype=np.float32)
        y = sd.invoke_subgraph(sub, [x], name="dropblock")
        sd.setLossVariables(y.name)
        feeds = {"x": np.ones((64, 64), np.float32)}
        # training-mode grads: ~half the entries must be zeroed by dropout
        g = sd.calculateGradients(feeds, ["x"])["x"]
        # calculateGradients runs train=False -> identity; exec the node
        # under the training path instead via the train step
        from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
        from deeplearning4j_tpu.train import updaters
        w_sd = SameDiff.create()
        xv = w_sd.var("w", np.ones((64, 64), np.float32))
        yv = w_sd.invoke_subgraph(sub, [xv], name="dropblock")
        w_sd.setLossVariables(yv.name)
        w_sd.placeHolder("ticker", shape=(None, 1), dtype=np.float32)
        w_sd.setTrainingConfig(TrainingConfig(
            updater=updaters.Sgd(1.0), data_set_feature_mapping=["ticker"],
            data_set_label_mapping=[]))
        w_sd.fit({"ticker": np.zeros((1, 1), np.float32)}, epochs=1)
        g = np.asarray(w_sd.getVariable("w").getArr())
        # after one SGD step from all-ones with loss=sum(dropout(w)):
        # dropped entries keep w==1 (grad 0), kept entries move by -2.0
        frac_unchanged = float(np.mean(np.isclose(g, 1.0)))
        assert 0.2 < frac_unchanged < 0.8, frac_unchanged
