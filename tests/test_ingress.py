"""Network front door (ISSUE 12): HTTP ingress, multi-model registry
with zero-drop hot-swap, and wire-level chaos.

The acceptance pins:

- **Hot-swap**: under sustained seeded load, rolling v1 -> v2 drops
  zero requests — every request resolves exactly once against exactly
  one version, steady-state recompiles stay 0 after the re-warm, and
  rollback restores v1 bit-identically.
- **Deadline propagation**: a wire ``deadline_ms`` that expires while
  queued is shed before dispatch and surfaces as 504 carrying the
  server-stamped latency.
- **Drain through the ingress**: SIGTERM with queued requests exits 0,
  the queued tail failing as retriable 503.
"""

import io
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import warnings
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.faults import ServingLoad, SwapSchedule
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.serving import (DecodePreset, HttpIngress,
                                        ModelNotFoundError, ModelRegistry,
                                        ModelServer, ServingRequest)
from deeplearning4j_tpu.train import updaters

NIN, NOUT = 4, 3
REPO = Path(__file__).resolve().parents[1]


def mlp(seed=42):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(updaters.Sgd(0.1)).list()
            .layer(DenseLayer(nOut=8, activation="relu"))
            .layer(OutputLayer(nOut=NOUT, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(NIN))
            .build())
    return MultiLayerNetwork(conf).init()


def feats(rows, seed=0):
    return np.random.RandomState(seed).randn(rows, NIN).astype(np.float32)


def post(url, path, body, headers=None, timeout=30.0):
    """POST returning (status, payload_dict, response_headers) — HTTP
    errors are outcomes here, not exceptions."""
    req = urllib.request.Request(f"{url}{path}", data=body,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def post_json(url, path, payload, headers=None, timeout=30.0):
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    return post(url, path, json.dumps(payload).encode(), h, timeout)


def get(url, path, timeout=10.0):
    try:
        with urllib.request.urlopen(f"{url}{path}", timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class _SlowModel:
    def __init__(self, base, service_s):
        self.base = base
        self.service_s = service_s

    def output(self, x):
        time.sleep(self.service_s)
        return self.base.output(x)


@pytest.fixture()
def net():
    return mlp()


# =============================================================== wire basics
class TestWireBasics:
    def test_json_predict_roundtrip(self, net):
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            with HttpIngress(reg, port=0) as ing:
                x = feats(2)
                code, payload, _ = post_json(
                    ing.url, "/v1/models/m:predict",
                    {"instances": x.tolist()})
                assert code == 200
                assert payload["model"] == "m"
                assert payload["version"] == 1
                assert payload["latency_ms"] > 0
                np.testing.assert_allclose(
                    np.asarray(payload["predictions"], np.float32),
                    np.asarray(net.output(x)), rtol=1e-5)

    def test_raw_tensor_body(self, net):
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            with HttpIngress(reg, port=0) as ing:
                x = feats(3, seed=5)
                code, payload, _ = post(
                    ing.url, "/v1/models/m:predict", x.tobytes(),
                    {"Content-Type": "application/octet-stream",
                     "X-Tensor-Shape": "3,4",
                     "X-Tensor-Dtype": "float32"})
                assert code == 200
                np.testing.assert_allclose(
                    np.asarray(payload["predictions"], np.float32),
                    np.asarray(net.output(x)), rtol=1e-5)

    def test_raw_tensor_size_mismatch_is_400(self, net):
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            with HttpIngress(reg, port=0) as ing:
                code, payload, _ = post(
                    ing.url, "/v1/models/m:predict", b"\x00" * 12,
                    {"Content-Type": "application/octet-stream",
                     "X-Tensor-Shape": "3,4"})
                assert code == 400 and "bytes" in payload["error"]

    def test_unknown_model_and_version_404(self, net):
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            with HttpIngress(reg, port=0) as ing:
                code, payload, _ = post_json(
                    ing.url, "/v1/models/nope:predict",
                    {"instances": feats(1).tolist()})
                assert code == 404 and "not loaded" in payload["error"]
                code, payload, _ = post_json(
                    ing.url, "/v1/models/m:predict?version=9",
                    {"instances": feats(1).tolist()})
                assert code == 404

    def test_malformed_json_400(self, net):
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            with HttpIngress(reg, port=0) as ing:
                code, payload, _ = post(
                    ing.url, "/v1/models/m:predict", b"not json",
                    {"Content-Type": "application/json"})
                assert code == 400
                code, payload, _ = post_json(
                    ing.url, "/v1/models/m:predict", {"rows": [[1]]})
                assert code == 400 and "instances" in payload["error"]

    def test_oversize_body_413(self, net):
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            ing = HttpIngress(reg, port=0, max_body_mb=0.0001).start()
            try:
                code, payload, _ = post_json(
                    ing.url, "/v1/models/m:predict",
                    {"instances": feats(8).tolist()})
                assert code == 413
            finally:
                ing.stop()

    def test_unknown_endpoints_404(self, net):
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            with HttpIngress(reg, port=0) as ing:
                assert get(ing.url, "/v2/whatever")[0] == 404
                assert post_json(ing.url, "/v1/models/m", {})[0] == 404

    def test_single_server_routes_as_default(self, net):
        sv = ModelServer(net, batch_limit=8, coalesce_ms=0.5)
        sv.warmup([(NIN,)])
        try:
            with HttpIngress(sv, port=0) as ing:
                x = feats(2)
                code, payload, _ = post_json(
                    ing.url, "/v1/models/default:predict",
                    {"instances": x.tolist()})
                assert code == 200 and payload["version"] == 1
                assert post_json(ing.url, "/v1/models/other:predict",
                                 {"instances": x.tolist()})[0] == 404
                code, models = get(ing.url, "/v1/models")
                assert code == 200 and "default" in models["models"]
        finally:
            sv.close()

    def test_models_and_health_endpoints(self, net):
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            with HttpIngress(reg, port=0) as ing:
                code, payload = get(ing.url, "/v1/models")
                assert code == 200
                m = payload["models"]["m"]
                assert m["active"] == 1
                assert m["versions"]["1"]["ready"] is True
                code, payload = get(ing.url, "/v1/models/m")
                assert code == 200 and payload["model"] == "m"
                assert get(ing.url, "/v1/models/nope")[0] == 404
                assert get(ing.url, "/healthz")[0] == 200
                assert get(ing.url, "/readyz")[0] == 200


# ============================================================== image bodies
class TestImageBodies:
    H = W = 16

    @staticmethod
    def _jpeg_bytes(side, seed=0):
        from PIL import Image
        rng = np.random.RandomState(seed)
        arr = rng.randint(0, 255, (side, side, 3), dtype=np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=90)
        return buf.getvalue()

    def _pixel_model(self):
        # per-channel mean over pixels: a forward whose output is an
        # exact function of the decoded tensor, so the wire path pins
        # the decode itself
        return lambda x: jnp.mean(x, axis=(2, 3))

    def test_decode_preset_from_pipeline(self):
        from deeplearning4j_tpu.data.pipeline import ImagePipeline
        pipe = (ImagePipeline.list(files=["unused.jpg"])
                .decode(height=self.H, width=self.W, channels=3)
                .batch(1))
        preset = DecodePreset.from_pipeline(pipe)
        assert (preset.height, preset.width, preset.channels) == \
            (self.H, self.W, 3)
        arr = preset.decode(self._jpeg_bytes(self.H))
        assert arr.shape == (1, 3, self.H, self.W)
        assert arr.dtype == np.float32
        assert 0.0 <= arr.min() and arr.max() <= 255.0

    def test_raw_jpeg_body_predicts(self):
        preset = DecodePreset(self.H, self.W, 3)
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("pix", self._pixel_model(), decode=preset,
                     shapes=[(3, self.H, self.W)])
            with HttpIngress(reg, port=0) as ing:
                body = self._jpeg_bytes(32, seed=3)   # resized on decode
                code, payload, _ = post(
                    ing.url, "/v1/models/pix:predict", body,
                    {"Content-Type": "image/jpeg"})
                assert code == 200
                want = np.asarray(preset.decode(body)).mean(axis=(2, 3))
                np.testing.assert_allclose(
                    np.asarray(payload["predictions"], np.float32),
                    want, rtol=1e-4)

    def test_scaled_preset(self):
        preset = DecodePreset(self.H, self.W, 3, scale=1.0 / 255.0)
        arr = preset.decode(self._jpeg_bytes(self.H, seed=1))
        assert arr.max() <= 1.0

    def test_image_body_without_preset_is_415(self, net):
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            with HttpIngress(reg, port=0) as ing:
                code, payload, _ = post(
                    ing.url, "/v1/models/m:predict",
                    self._jpeg_bytes(self.H),
                    {"Content-Type": "image/jpeg"})
                assert code == 415
                assert "decode preset" in payload["error"]


# ======================================================= deadline propagation
class TestDeadlineWire:
    def test_wire_deadline_expired_while_queued_is_504(self, net):
        """THE deadline pin: deadline_ms -> ServingRequest deadline; an
        expiry while queued sheds BEFORE dispatch and surfaces as 504
        with the server-stamped wait."""
        sv = ModelServer(_SlowModel(net, 0.15), batch_limit=1, max_queue=16,
                         coalesce_ms=0.0)
        sv.warmup([(NIN,)])
        try:
            with HttpIngress(sv, port=0) as ing:
                # saturate the single-slot server so a queued request's
                # 30ms budget burns before dispatch
                blockers, threads = [], []
                for i in range(3):
                    t = threading.Thread(
                        target=lambda i=i: blockers.append(post_json(
                            ing.url, "/v1/models/default:predict",
                            {"instances": feats(1, seed=i).tolist()})))
                    t.start()
                    threads.append(t)
                time.sleep(0.03)
                code, payload, _ = post_json(
                    ing.url, "/v1/models/default:predict",
                    {"instances": feats(1, seed=99).tolist()},
                    headers={"deadline_ms": "30"})
                for t in threads:
                    t.join(30.0)
                assert code == 504
                assert payload["type"] == "DeadlineExceededError"
                assert payload["retriable"] is False
                # server-stamped: at least the deadline elapsed, and the
                # stamp came from the server's own clock
                assert payload["latency_ms"] >= 30.0
                assert all(c == 200 for c, _, _ in blockers)
        finally:
            sv.close()

    def test_deadline_in_json_body(self, net):
        sv = ModelServer(_SlowModel(net, 0.15), batch_limit=1, max_queue=16,
                         coalesce_ms=0.0)
        sv.warmup([(NIN,)])
        try:
            with HttpIngress(sv, port=0) as ing:
                done = []
                t = threading.Thread(target=lambda: done.append(post_json(
                    ing.url, "/v1/models/default:predict",
                    {"instances": feats(1).tolist()})))
                t.start()
                time.sleep(0.03)
                code, payload, _ = post_json(
                    ing.url, "/v1/models/default:predict",
                    {"instances": feats(1, seed=9).tolist(),
                     "deadline_ms": 25})
                t.join(30.0)
                assert code == 504
        finally:
            sv.close()

    def test_bad_deadline_is_400(self, net):
        sv = ModelServer(net, batch_limit=8, coalesce_ms=0.5)
        sv.warmup([(NIN,)])
        try:
            with HttpIngress(sv, port=0) as ing:
                code, payload, _ = post_json(
                    ing.url, "/v1/models/default:predict",
                    {"instances": feats(1).tolist()},
                    headers={"deadline_ms": "-5"})
                assert code == 400 and "deadline_ms" in payload["error"]
        finally:
            sv.close()

    def test_generous_deadline_completes(self, net):
        sv = ModelServer(net, batch_limit=8, coalesce_ms=0.5)
        sv.warmup([(NIN,)])
        try:
            with HttpIngress(sv, port=0) as ing:
                code, payload, _ = post_json(
                    ing.url, "/v1/models/default:predict",
                    {"instances": feats(1).tolist()},
                    headers={"X-Deadline-Ms": "5000"})
                assert code == 200
        finally:
            sv.close()


# ========================================================= wire error taxonomy
class TestWireTaxonomy:
    def test_overload_is_429_with_retry_after(self, net):
        sv = ModelServer(_SlowModel(net, 0.2), batch_limit=1, max_queue=2,
                         coalesce_ms=0.0)
        sv.warmup([(NIN,)])
        try:
            with HttpIngress(sv, port=0) as ing:
                results, threads = [], []
                for i in range(8):
                    t = threading.Thread(
                        target=lambda i=i: results.append(post_json(
                            ing.url, "/v1/models/default:predict",
                            {"instances": feats(1, seed=i).tolist()},
                            timeout=60)))
                    t.start()
                    threads.append(t)
                time.sleep(0.08)
                code, payload, hdrs = post_json(
                    ing.url, "/v1/models/default:predict",
                    {"instances": feats(1, seed=99).tolist()})
                for t in threads:
                    t.join(60.0)
                assert code == 429
                assert payload["type"] == "ServerOverloadedError"
                assert payload["retriable"] is True
                assert float(hdrs["Retry-After"]) > 0
        finally:
            sv.close()

    def test_draining_is_503_retriable(self, net):
        sv = ModelServer(net, batch_limit=8, coalesce_ms=0.5)
        sv.warmup([(NIN,)])
        try:
            with HttpIngress(sv, port=0) as ing:
                sv.drain()
                code, payload, hdrs = post_json(
                    ing.url, "/v1/models/default:predict",
                    {"instances": feats(1).tolist()})
                assert code == 503
                assert payload["type"] == "ServerDrainingError"
                assert payload["retriable"] is True
                assert "Retry-After" in hdrs
                assert get(ing.url, "/readyz")[0] == 503
        finally:
            sv.close()

    def test_breaker_open_is_503_with_cooldown_retry_after(self, net):
        class Failing:
            def __init__(self):
                self.arm = False

            def output(self, x):
                if self.arm:
                    raise RuntimeError("injected dispatch failure")
                return net.output(x)

        model = Failing()
        sv = ModelServer(model, batch_limit=8, coalesce_ms=0.0,
                         breaker_threshold=1, breaker_cooldown=30.0,
                         max_retries=0)
        sv.warmup([(NIN,)])
        try:
            with HttpIngress(sv, port=0) as ing:
                model.arm = True
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    code, payload, _ = post_json(
                        ing.url, "/v1/models/default:predict",
                        {"instances": feats(1).tolist()})
                assert code == 500      # the dispatch failure itself
                deadline = time.monotonic() + 5.0
                while sv.breaker.state != "open" \
                        and time.monotonic() < deadline:
                    time.sleep(0.01)
                code, payload, hdrs = post_json(
                    ing.url, "/v1/models/default:predict",
                    {"instances": feats(1).tolist()})
                assert code == 503
                assert payload["type"] == "ServerUnhealthyError"
                assert payload["retriable"] is True
                # Retry-After carries the breaker's own cooldown hint
                assert 0 < float(hdrs["Retry-After"]) <= 30.0
                assert get(ing.url, "/healthz")[0] == 503
        finally:
            sv.close()

    def test_oversize_batch_is_400(self, net):
        sv = ModelServer(net, batch_limit=4, coalesce_ms=0.5)
        sv.warmup([(NIN,)])
        try:
            with HttpIngress(sv, port=0) as ing:
                code, payload, _ = post_json(
                    ing.url, "/v1/models/default:predict",
                    {"instances": feats(6).tolist()})
                assert code == 400 and "batch_limit" in payload["error"]
        finally:
            sv.close()


# ================================================================== hot-swap
class TestHotSwap:
    """THE zero-drop hot-swap acceptance pin."""

    def test_zero_drop_roll_under_sustained_load(self):
        net1, net2 = mlp(42), mlp(43)
        reg = ModelRegistry(batch_limit=8, max_queue=256, coalesce_ms=0.5)
        try:
            reg.load("m", net1, shapes=[(NIN,)])
            load = ServingLoad.seeded(11, mix="steady", n=120, rps=300.0,
                                      max_rows=2)
            handles = []

            def submit(x, deadline=None):
                h = reg.submit("m", x, deadline=deadline)
                handles.append(h)
                return h

            replay = threading.Thread(
                target=lambda: load.replay(submit, (NIN,), rng_seed=5))
            replay.start()
            # v2 warms its whole ladder while v1 carries the load, then
            # the route rolls atomically mid-replay
            reg.load("m", net2)             # inherits v1's warm shapes
            prev = reg.roll("m")
            assert prev == 1
            replay.join(60.0)
            assert not replay.is_alive()
            assert len(handles) == len(load)

            # zero drops, exactly-once, exactly-one-version
            v1 = v2 = 0
            for h in handles:
                out = h.get(30.0)           # nothing errored
                assert h.resolutions == 1
                assert h.server in ("m:v1", "m:v2")
                if h.server == "m:v1":
                    v1 += 1
                else:
                    v2 += 1
                # the answer really came from the version that admitted
                # it: re-ask that version directly, pinned
                want = reg.server("m", 1 if h.server == "m:v1" else 2) \
                    .output(h.features, timeout=30.0)
                np.testing.assert_array_equal(out, want)
            assert v1 > 0 and v2 > 0, (v1, v2)

            # steady-state recompiles stayed 0 on BOTH versions
            assert reg.server("m", 1).recompiles_after_warmup() == 0
            assert reg.server("m", 2).recompiles_after_warmup() == 0
        finally:
            reg.close()

    def test_rollback_restores_v1_bit_identically(self):
        net1, net2 = mlp(42), mlp(43)
        x = feats(4, seed=21)
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net1, shapes=[(NIN,)])
            before = np.asarray(reg.output("m", x))
            reg.load("m", net2)
            reg.roll("m")
            rolled = np.asarray(reg.output("m", x))
            assert not np.array_equal(before, rolled)
            assert reg.server("m", 2).recompiles_after_warmup() == 0
            reg.rollback("m")
            after = np.asarray(reg.output("m", x))
            # SAME server object, SAME compiled programs: bitwise equal
            np.testing.assert_array_equal(before, after)
            assert reg.server("m", 1).recompiles_after_warmup() == 0

    def test_roll_does_not_drain_the_old_version(self, net):
        # requests queued on v1 when the roll lands must complete on v1
        reg = ModelRegistry(batch_limit=1, max_queue=32, coalesce_ms=0.0)
        try:
            reg.load("m", _SlowModel(net, 0.1), shapes=[(NIN,)])
            reqs = [reg.submit("m", feats(1, seed=i)) for i in range(5)]
            reg.load("m", net, shapes=[(NIN,)])
            reg.roll("m")
            post_roll = reg.submit("m", feats(1, seed=9))
            for r in reqs:
                r.get(30.0)
                assert r.server == "m:v1" and r.resolutions == 1
            post_roll.get(30.0)
            assert post_roll.server == "m:v2"
        finally:
            reg.close()

    def test_retire_waits_and_refuses_active(self, net):
        reg = ModelRegistry(batch_limit=8, coalesce_ms=0.5)
        try:
            reg.load("m", net, shapes=[(NIN,)])
            reg.load("m", net)
            with pytest.raises(ValueError, match="active"):
                reg.retire("m", 1)
            reg.roll("m")
            reg.retire("m", 1)
            with pytest.raises(ModelNotFoundError):
                reg.server("m", 1)
            with pytest.raises(ValueError, match="no previous"):
                reg.rollback("m")
        finally:
            reg.close()

    def test_swap_schedule_storm_over_the_wire(self):
        """Seeded swap-under-load chaos THROUGH the ingress: rolls and
        rollbacks land mid-replay over real sockets; every answered
        request carries a consistent version stamp and constant-output
        prediction, and none is dropped."""
        v1 = lambda x: jnp.full((x.shape[0], 1), 1.0)   # noqa: E731
        v2 = lambda x: jnp.full((x.shape[0], 1), 2.0)   # noqa: E731
        reg = ModelRegistry(batch_limit=8, max_queue=256, coalesce_ms=0.5)
        try:
            reg.load("c", v1, shapes=[(NIN,)])
            reg.load("c", v2)
            with HttpIngress(reg, port=0) as ing:
                load = ServingLoad.seeded(23, mix="steady", n=60,
                                          rps=150.0, max_rows=2)
                swaps = SwapSchedule.seeded(7, "c", load.duration(),
                                            n_swaps=3).start(reg)
                results = load.replay_http(ing.url, "c", (NIN,))
                performed = swaps.join(30.0)
            assert len(performed) == 3
            assert all(a in ("roll", "rollback") for _, _, a, _ in performed)
            assert len(results) == len(load)
            for spec, outcome in results:
                assert not isinstance(outcome, Exception), outcome
                code, payload = outcome
                assert code == 200
                val = np.asarray(payload["predictions"])[0, 0]
                ver = payload["version"]
                assert (val, ver) in ((1.0, 1), (2.0, 2)), (val, ver)
        finally:
            reg.close()


# ================================================================ wire chaos
class TestWireChaos:
    def test_slow_clients_do_not_block_fast_ones(self, net):
        sv = ModelServer(net, batch_limit=8, coalesce_ms=0.5)
        sv.warmup([(NIN,)])
        try:
            with HttpIngress(sv, port=0) as ing:
                load = ServingLoad.seeded(31, mix="steady", n=12, rps=100.0,
                                          max_rows=2, slow_frac=0.5,
                                          slow_client_seconds=0.3)
                assert any(s.slow_s > 0 for s in load)
                t0 = time.monotonic()
                chaos = threading.Thread(
                    target=lambda: load.replay_http(ing.url, "default",
                                                    (NIN,)))
                chaos.start()
                time.sleep(0.05)
                # a well-behaved client mid-storm answers promptly
                code, payload, _ = post_json(
                    ing.url, "/v1/models/default:predict",
                    {"instances": feats(1).tolist()})
                fast_latency = time.monotonic() - t0
                chaos.join(60.0)
                assert code == 200
                assert fast_latency < 2.0
        finally:
            sv.close()

    def test_mid_flight_disconnects_are_absorbed(self, net):
        from deeplearning4j_tpu import profiler as prof
        sv = ModelServer(net, batch_limit=8, coalesce_ms=0.5)
        sv.warmup([(NIN,)])
        try:
            with HttpIngress(sv, port=0) as ing:
                before = prof.get_registry().get(
                    "dl4j_ingress_disconnects_total").value
                load = ServingLoad.seeded(37, mix="steady", n=16, rps=200.0,
                                          max_rows=2, disconnect_frac=0.4)
                n_disc = sum(1 for s in load if s.disconnect)
                assert n_disc > 0
                results = load.replay_http(ing.url, "default", (NIN,))
                disc = [o for _, o in results if o == "disconnected"]
                answered = [o for _, o in results
                            if isinstance(o, tuple)]
                assert len(disc) == n_disc
                assert all(code == 200 for code, _ in answered)
                # the server noticed and moved on; later traffic is fine
                deadline = time.monotonic() + 5.0
                while prof.get_registry().get(
                        "dl4j_ingress_disconnects_total").value < \
                        before + n_disc and time.monotonic() < deadline:
                    time.sleep(0.05)
                assert prof.get_registry().get(
                    "dl4j_ingress_disconnects_total").value >= \
                    before + n_disc
                code, _, _ = post_json(
                    ing.url, "/v1/models/default:predict",
                    {"instances": feats(1).tolist()})
                assert code == 200
        finally:
            sv.close()


# ============================================================== load endpoint
class TestLoadEndpoint:
    def test_v1_load_structure_and_gauges(self, net):
        from deeplearning4j_tpu import profiler as prof
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            reg.output("m", feats(2))
            with HttpIngress(reg, port=0) as ing:
                code, payload = get(ing.url, "/v1/load")
            assert code == 200
            m = payload["models"]["m"]
            assert m["version"] == 1
            assert m["queue_depth"] == 0
            assert m["breaker"] == "closed"
            assert m["shed_rate"] == 0.0
            assert m["batch_occupancy_mean"] is not None
            totals = payload["totals"]
            assert totals["ready"] is True
            assert totals["breakers_open"] == 0
            # the same hints exported as gauges
            g = prof.get_registry().get("dl4j_serving_shed_ratio")
            assert g.labels(server="m:v1").value == 0.0
            g = prof.get_registry().get("dl4j_serving_batch_occupancy_mean")
            assert g.labels(server="m:v1").value > 0


# ============================================================= registry lint
class TestRegistryRollLint:
    def test_w111_on_unwarmed_roll_target(self, net):
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            reg.load("m", mlp(43), warm=False, shapes=None)
            report = reg.validate_roll("m")
            assert "DL4J-W111" in report.codes()
            with pytest.warns(UserWarning, match="W111"):
                reg.roll("m")

    def test_w111_on_missing_shapes(self):
        # dimension-agnostic forwards so both shapes genuinely warm
        fwd = lambda x: jnp.sum(x, axis=-1, keepdims=True)  # noqa: E731
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", fwd, shapes=[(NIN,), (NIN + 1,)])
            reg.load("m", fwd, shapes=[(NIN,)])
            report = reg.validate_roll("m")
            assert "DL4J-W111" in report.codes()

    def test_clean_roll_lints_clean(self, net):
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            reg.load("m", mlp(43))
            assert reg.validate_roll("m").codes() == []

    def test_strict_roll_refuses_w111(self, net):
        from deeplearning4j_tpu.analysis import ModelValidationError
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            reg.load("m", mlp(43), warm=False)
            with pytest.raises(ModelValidationError):
                reg.roll("m", strict=True)
            assert reg.active_version("m") == 1

    def test_w111_documented(self):
        from deeplearning4j_tpu.analysis import DIAGNOSTIC_CODES
        assert "DL4J-W111" in DIAGNOSTIC_CODES


# ======================================================== drain through wire
class TestIngressDrain:
    def test_sigterm_through_ingress_exits_zero(self, tmp_path):
        """THE drain pin, through the wire: a real process serving HTTP
        takes SIGTERM under load; queued requests fail as retriable 503,
        in-flight work completes, exit code 0."""
        script = tmp_path / "ingress_sigterm.py"
        script.write_text(
            "import json, os, threading, time, urllib.error\n"
            "import urllib.request\n"
            "import numpy as np\n"
            "from deeplearning4j_tpu.nn import (InputType,\n"
            "    MultiLayerNetwork, NeuralNetConfiguration)\n"
            "from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer\n"
            "from deeplearning4j_tpu.serving import HttpIngress, ModelServer\n"
            "conf = (NeuralNetConfiguration.Builder().seed(0).list()\n"
            "        .layer(DenseLayer(nOut=8, activation='relu'))\n"
            "        .layer(OutputLayer(nOut=3, lossFunction='mcxent',\n"
            "                           activation='softmax'))\n"
            "        .setInputType(InputType.feedForward(4)).build())\n"
            "net = MultiLayerNetwork(conf).init()\n"
            "class Slow:\n"
            "    def output(self, x):\n"
            "        time.sleep(0.1)\n"
            "        return net.output(x)\n"
            "sv = ModelServer(Slow(), batch_limit=1, max_queue=64,\n"
            "                 coalesce_ms=0.0, preemption=True)\n"
            "sv.warmup([(4,)])\n"
            "ing = HttpIngress(sv, port=0).start()\n"
            "body = json.dumps({'instances': [[0.0, 0.0, 0.0, 0.0]]})\\\n"
            "    .encode()\n"
            "results = []\n"
            "def one():\n"
            "    req = urllib.request.Request(\n"
            "        ing.url + '/v1/models/default:predict', data=body,\n"
            "        headers={'Content-Type': 'application/json'})\n"
            "    try:\n"
            "        with urllib.request.urlopen(req, timeout=60) as r:\n"
            "            results.append((r.status, json.loads(r.read())))\n"
            "    except urllib.error.HTTPError as e:\n"
            "        results.append((e.code, json.loads(e.read())))\n"
            "threads = [threading.Thread(target=one) for _ in range(16)]\n"
            "for t in threads:\n"
            "    t.start()\n"
            "time.sleep(0.25)  # some dispatched, most still queued\n"
            "os.kill(os.getpid(), 15)  # SIGTERM mid-load\n"
            "for t in threads:\n"
            "    t.join(90)\n"
            "codes = [c for c, _ in results]\n"
            "assert len(codes) == 16, codes\n"
            "ok = codes.count(200)\n"
            "drained = [p for c, p in results if c == 503]\n"
            "assert ok >= 1, codes\n"
            "assert drained, codes\n"
            "assert all(p['type'] == 'ServerDrainingError'\n"
            "           and p['retriable'] is True for p in drained)\n"
            "sv.close()\n"
            "ing.stop()\n"
            "print('DRAINED', ok, len(drained), flush=True)\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
        proc = subprocess.run([sys.executable, str(script)],
                              capture_output=True, text=True, timeout=180,
                              env=env, cwd=str(REPO))
        assert proc.returncode == 0, proc.stderr
        assert "DRAINED" in proc.stdout


# ========================================================== request ownership
class TestRequestOwnership:
    def test_request_stamped_with_server(self, net):
        sv = ModelServer(net, batch_limit=8, coalesce_ms=0.5, name="owner")
        sv.warmup([(NIN,)])
        try:
            r = sv.submit(feats(1))
            assert isinstance(r, ServingRequest)
            assert r.server == "owner"
            r.get(30.0)
        finally:
            sv.close()


# ======================================================= review-hardening pins
class TestReviewHardening:
    def test_oversize_refusal_closes_keepalive_connection(self, net):
        """A 413 that left the unread body on a persistent connection
        would desync the stream — the refusal must close it."""
        import http.client
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            ing = HttpIngress(reg, port=0, max_body_mb=0.0001).start()
            try:
                conn = http.client.HTTPConnection("127.0.0.1", ing.port,
                                                  timeout=10)
                body = json.dumps(
                    {"instances": feats(8).tolist()}).encode()
                conn.request("POST", "/v1/models/m:predict", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 413
                resp.read()
                assert resp.will_close   # Connection: close advertised
                conn.close()
            finally:
                ing.stop()

    def test_malformed_version_query_is_400(self, net):
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(NIN,)])
            with HttpIngress(reg, port=0) as ing:
                code, payload, _ = post_json(
                    ing.url, "/v1/models/m:predict?version=abc",
                    {"instances": feats(1).tolist()})
                assert code == 400 and "version" in payload["error"]

    def test_concurrent_loads_reserve_distinct_versions(self):
        """Two racing load()s of the same name must not pick the same
        version number while one warms outside the registry lock."""
        fwd = lambda x: jnp.tanh(x)                     # noqa: E731
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", fwd, shapes=[(NIN,)])
            got, errs = [], []

            def one():
                try:
                    got.append(reg.load("m", fwd, shapes=[(NIN,)],
                                        roll=False))
                except Exception as e:          # surfaced to the assert
                    errs.append(e)

            ts = [threading.Thread(target=one) for _ in range(2)]
            [t.start() for t in ts]
            [t.join(60.0) for t in ts]
            assert not errs, errs
            assert sorted(got) == [2, 3]
            assert reg.server("m", 2) is not reg.server("m", 3)

    def test_retire_timeout_never_fails_queued_requests(self, net):
        reg = ModelRegistry(batch_limit=1, max_queue=32, coalesce_ms=0.0)
        try:
            reg.load("m", _SlowModel(net, 0.15), shapes=[(NIN,)])
            reqs = [reg.submit("m", feats(1, seed=i)) for i in range(4)]
            reg.load("m", net, shapes=[(NIN,)])
            reg.roll("m")
            with pytest.raises(TimeoutError, match="still queued"):
                reg.retire("m", 1, timeout=0.05)
            # v1 kept serving: every queued request still completes
            for r in reqs:
                r.get(30.0)
                assert r.resolutions == 1
            reg.retire("m", 1, timeout=30.0)    # queue drained: clean
        finally:
            reg.close()
