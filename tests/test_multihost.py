"""Multi-host wiring tests: 2 real processes on one machine, wired into a
single global device mesh via ``initializeDistributed`` (gloo CPU
collectives), per-process data sharding, and the sharded checkpoint
layout.

Reference parity: SURVEY.md §5 "Distributed communication backend" / §7
hard-part #7 — the reference proves its Spark+Aeron plumbing with
multi-worker integration tests; here two OS processes really rendezvous,
train the same SPMD step on a mesh spanning both, and checkpoint/restore
shard-wise.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["DL4J_REPO"])
import numpy as np

# the environment's TPU bootstrap (sitecustomize) pins jax_platforms to the
# TPU plugin; pin back to CPU BEFORE the backend initializes (same move as
# tests/conftest.py)
import jax
jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu.parallel.init import initializeDistributed
info = initializeDistributed()
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.data.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.parallel.data import (ShardedDataSetIterator,
                                              make_global_view)
from deeplearning4j_tpu.parallel import checkpoint as ckpt

assert info.process_count == 2, info
assert info.global_device_count == 4, info

mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("data",))

# deterministic global dataset, identical on both ranks
rng = np.random.RandomState(0)
X = rng.randn(16, 8).astype(np.float32)
W_true = rng.randn(8, 1).astype(np.float32)
Y = X @ W_true
base = ListDataSetIterator(DataSet(X, Y), batch_size=16)
it = ShardedDataSetIterator(base)
assert it.batch() == 8

params = {"W": jnp.zeros((8, 1), jnp.float32)}
rep = NamedSharding(mesh, P())
params = jax.device_put(params, rep)

@jax.jit
def step(params, x, y):
    def loss_fn(p):
        return jnp.mean((x @ p["W"] - y) ** 2)
    l, g = jax.value_and_grad(loss_fn)(params)
    return jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg, params, g), l

losses = []
for _ in range(12):
    it.reset()
    while it.hasNext():
        ds = it.next()
        x = make_global_view(ds.features, mesh, P("data"))
        y = make_global_view(ds.labels, mesh, P("data"))
        params, l = step(params, x, y)
        losses.append(float(l))

out_dir = os.environ["DL4J_CKPT_DIR"]
ckpt.save_sharded(out_dir, params, step=12)

# restore into the same sharding and verify
restored, got_step = ckpt.load_sharded(out_dir, params)
np.testing.assert_allclose(np.asarray(restored["W"]),
                           np.asarray(params["W"]), rtol=0, atol=0)
assert got_step == 12

print("RESULT " + json.dumps({
    "rank": info.process_index,
    "losses": [round(v, 8) for v in losses],
    "w_sum": float(np.asarray(params["W"]).sum()),
}))
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_process_train_and_checkpoint(tmp_path):
    port = _free_port()
    ckpt_dir = str(tmp_path / "ckpt")
    worker = str(tmp_path / "worker.py")
    with open(worker, "w") as f:
        f.write(_WORKER)
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "DL4J_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "DL4J_TPU_NUM_PROCESSES": "2",
            "DL4J_TPU_PROCESS_ID": str(rank),
            "DL4J_REPO": os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            "DL4J_CKPT_DIR": ckpt_dir,
        })
        procs.append(subprocess.Popen([sys.executable, worker],
                                      stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT, env=env,
                                      text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process workers timed out")
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    results = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("RESULT ")][-1]
        results.append(json.loads(line[len("RESULT "):]))
    assert {r["rank"] for r in results} == {0, 1}
    # SPMD: both processes computed identical global losses and params
    assert results[0]["losses"] == results[1]["losses"]
    assert results[0]["w_sum"] == pytest.approx(results[1]["w_sum"])
    # training converged on the global (not process-local) problem
    assert results[0]["losses"][-1] < results[0]["losses"][0] * 0.1
    # both processes' shard files exist + one merged manifest
    files = os.listdir(ckpt_dir)
    assert "manifest.json" in files
    assert "shards_p0.npz" in files and "shards_p1.npz" in files


class TestShardedCheckpointSingleProcess:
    """Same layout on the 8-virtual-device mesh: sharded leaves write one
    shard per device index; load assembles exactly the addressable set."""

    def test_sharded_params_roundtrip(self, tmp_path):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel import checkpoint as ckpt

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        tree = {
            "W": jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                                NamedSharding(mesh, P("data"))),
            "b": jax.device_put(jnp.ones((8,)), NamedSharding(mesh, P())),
            "step_count": 7,   # non-array leaf
        }
        d = str(tmp_path / "ck")
        ckpt.save_sharded(d, tree, step=3)
        restored, step = ckpt.load_sharded(d, tree)
        assert step == 3
        np.testing.assert_array_equal(np.asarray(restored["W"]),
                                      np.asarray(tree["W"]))
        np.testing.assert_array_equal(np.asarray(restored["b"]),
                                      np.asarray(tree["b"]))
        # shardings preserved; scalar leaves keep their Python type
        assert restored["W"].sharding.spec == P("data")
        assert restored["step_count"] == 7
        assert isinstance(restored["step_count"], int)

    def test_sharded_save_into_host_tree_assembles_all_shards(self, tmp_path):
        """ADVICE r3 (medium): restoring a sharded checkpoint into a plain
        numpy/host target must assemble the FULL global array, not silently
        return the first shard's slice."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel import checkpoint as ckpt

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        full = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        tree = {"W": jax.device_put(full, NamedSharding(mesh, P("data")))}
        d = str(tmp_path / "ck3")
        ckpt.save_sharded(d, tree, step=1)
        # target is a host numpy tree: no sharding info at all
        restored, step = ckpt.load_sharded(d, {"W": np.zeros((8, 8),
                                                            np.float32)})
        assert step == 1
        assert restored["W"].shape == (8, 8)
        np.testing.assert_array_equal(np.asarray(restored["W"]),
                                      np.asarray(full))

    def test_topology_change_reshards_on_load(self, tmp_path):
        # ISSUE 6: a checkpoint saved under one mesh layout loads under
        # another — each target shard is stitched from the saved shards
        # (the elastic shrink/grow resume path)
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel import checkpoint as ckpt

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        rng = np.random.RandomState(3)
        full = rng.randn(8, 4).astype(np.float32)
        sharded = jax.device_put(jnp.asarray(full),
                                 NamedSharding(mesh, P("data")))
        d = str(tmp_path / "ck2")
        ckpt.save_sharded(d, {"W": sharded})
        # replicated target: the full array assembles from the 8 shards
        repl = jax.device_put(jnp.zeros((8, 4)), NamedSharding(mesh, P()))
        restored, _ = ckpt.load_sharded(d, {"W": repl})
        np.testing.assert_array_equal(np.asarray(restored["W"]), full)
        # 4-device shrunk mesh: each wider shard stitches from two saved
        half = Mesh(np.array(jax.devices()[:4]).reshape(4), ("data",))
        tgt = jax.device_put(jnp.zeros((8, 4)), NamedSharding(half, P("data")))
        restored, _ = ckpt.load_sharded(d, {"W": tgt})
        np.testing.assert_array_equal(np.asarray(restored["W"]), full)
        assert len(restored["W"].sharding.device_set) == 4

    def test_uncoverable_topology_still_fails_loudly(self, tmp_path):
        # shards that genuinely can't tile the requested slice (a shard
        # missing from the manifest) must raise, never return garbage
        import jax
        import jax.numpy as jnp
        import json
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel import checkpoint as ckpt

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        sharded = jax.device_put(jnp.zeros((8, 4)),
                                 NamedSharding(mesh, P("data")))
        d = str(tmp_path / "ck4")
        ckpt.save_sharded(d, {"W": sharded})
        man = os.path.join(d, "manifest.json")
        with open(man) as f:
            manifest = json.load(f)
        manifest["leaves"]["W"]["shards"].pop("0:1;0:4")
        with open(man, "w") as f:
            json.dump(manifest, f)
        repl = jax.device_put(jnp.zeros((8, 4)), NamedSharding(mesh, P()))
        with pytest.raises(FileNotFoundError, match="cover only"):
            ckpt.load_sharded(d, {"W": repl})
