"""Multi-host wiring tests: 2 real processes on one machine, wired into a
single global device mesh via ``initializeDistributed`` (gloo CPU
collectives), per-process data sharding, and the sharded checkpoint
layout — plus (ISSUE 15 tier 3, ``pytest -m multihost``) the socket/file
CoordinationService: 2 OS worker processes rendezvous at the PR-6 resume
barrier over TCP, agree on the min step bit-exactly like the in-process
coordinator, and a peer that stops heartbeating surfaces the structured
dead-peer error instead of N independent timeouts.

Reference parity: SURVEY.md §5 "Distributed communication backend" / §7
hard-part #7 — the reference proves its Spark+Aeron plumbing with
multi-worker integration tests; here two OS processes really rendezvous,
train the same SPMD step on a mesh spanning both, and checkpoint/restore
shard-wise.
"""

import json
import os
import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["DL4J_REPO"])
import numpy as np

# the environment's TPU bootstrap (sitecustomize) pins jax_platforms to the
# TPU plugin; pin back to CPU BEFORE the backend initializes (same move as
# tests/conftest.py)
import jax
jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu.parallel.init import initializeDistributed
info = initializeDistributed()
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.data.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.parallel.data import (ShardedDataSetIterator,
                                              make_global_view)
from deeplearning4j_tpu.parallel import checkpoint as ckpt

assert info.process_count == 2, info
assert info.global_device_count == 4, info

mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("data",))

# deterministic global dataset, identical on both ranks
rng = np.random.RandomState(0)
X = rng.randn(16, 8).astype(np.float32)
W_true = rng.randn(8, 1).astype(np.float32)
Y = X @ W_true
base = ListDataSetIterator(DataSet(X, Y), batch_size=16)
it = ShardedDataSetIterator(base)
assert it.batch() == 8

params = {"W": jnp.zeros((8, 1), jnp.float32)}
rep = NamedSharding(mesh, P())
params = jax.device_put(params, rep)

@jax.jit
def step(params, x, y):
    def loss_fn(p):
        return jnp.mean((x @ p["W"] - y) ** 2)
    l, g = jax.value_and_grad(loss_fn)(params)
    return jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg, params, g), l

losses = []
for _ in range(12):
    it.reset()
    while it.hasNext():
        ds = it.next()
        x = make_global_view(ds.features, mesh, P("data"))
        y = make_global_view(ds.labels, mesh, P("data"))
        params, l = step(params, x, y)
        losses.append(float(l))

out_dir = os.environ["DL4J_CKPT_DIR"]
ckpt.save_sharded(out_dir, params, step=12)

# restore into the same sharding and verify
restored, got_step = ckpt.load_sharded(out_dir, params)
np.testing.assert_allclose(np.asarray(restored["W"]),
                           np.asarray(params["W"]), rtol=0, atol=0)
assert got_step == 12

print("RESULT " + json.dumps({
    "rank": info.process_index,
    "losses": [round(v, 8) for v in losses],
    "w_sum": float(np.asarray(params["W"]).sum()),
}))
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.multihost
def test_two_process_train_and_checkpoint(tmp_path):
    port = _free_port()
    ckpt_dir = str(tmp_path / "ckpt")
    worker = str(tmp_path / "worker.py")
    with open(worker, "w") as f:
        f.write(_WORKER)
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "DL4J_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "DL4J_TPU_NUM_PROCESSES": "2",
            "DL4J_TPU_PROCESS_ID": str(rank),
            "DL4J_REPO": os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            "DL4J_CKPT_DIR": ckpt_dir,
        })
        procs.append(subprocess.Popen([sys.executable, worker],
                                      stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT, env=env,
                                      text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process workers timed out")
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    results = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("RESULT ")][-1]
        results.append(json.loads(line[len("RESULT "):]))
    assert {r["rank"] for r in results} == {0, 1}
    # SPMD: both processes computed identical global losses and params
    assert results[0]["losses"] == results[1]["losses"]
    assert results[0]["w_sum"] == pytest.approx(results[1]["w_sum"])
    # training converged on the global (not process-local) problem
    assert results[0]["losses"][-1] < results[0]["losses"][0] * 0.1
    # both processes' shard files exist + one merged manifest
    files = os.listdir(ckpt_dir)
    assert "manifest.json" in files
    assert "shards_p0.npz" in files and "shards_p1.npz" in files


class TestShardedCheckpointSingleProcess:
    """Same layout on the 8-virtual-device mesh: sharded leaves write one
    shard per device index; load assembles exactly the addressable set."""

    def test_sharded_params_roundtrip(self, tmp_path):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel import checkpoint as ckpt

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        tree = {
            "W": jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                                NamedSharding(mesh, P("data"))),
            "b": jax.device_put(jnp.ones((8,)), NamedSharding(mesh, P())),
            "step_count": 7,   # non-array leaf
        }
        d = str(tmp_path / "ck")
        ckpt.save_sharded(d, tree, step=3)
        restored, step = ckpt.load_sharded(d, tree)
        assert step == 3
        np.testing.assert_array_equal(np.asarray(restored["W"]),
                                      np.asarray(tree["W"]))
        np.testing.assert_array_equal(np.asarray(restored["b"]),
                                      np.asarray(tree["b"]))
        # shardings preserved; scalar leaves keep their Python type
        assert restored["W"].sharding.spec == P("data")
        assert restored["step_count"] == 7
        assert isinstance(restored["step_count"], int)

    def test_sharded_save_into_host_tree_assembles_all_shards(self, tmp_path):
        """ADVICE r3 (medium): restoring a sharded checkpoint into a plain
        numpy/host target must assemble the FULL global array, not silently
        return the first shard's slice."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel import checkpoint as ckpt

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        full = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        tree = {"W": jax.device_put(full, NamedSharding(mesh, P("data")))}
        d = str(tmp_path / "ck3")
        ckpt.save_sharded(d, tree, step=1)
        # target is a host numpy tree: no sharding info at all
        restored, step = ckpt.load_sharded(d, {"W": np.zeros((8, 8),
                                                            np.float32)})
        assert step == 1
        assert restored["W"].shape == (8, 8)
        np.testing.assert_array_equal(np.asarray(restored["W"]),
                                      np.asarray(full))

    def test_topology_change_reshards_on_load(self, tmp_path):
        # ISSUE 6: a checkpoint saved under one mesh layout loads under
        # another — each target shard is stitched from the saved shards
        # (the elastic shrink/grow resume path)
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel import checkpoint as ckpt

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        rng = np.random.RandomState(3)
        full = rng.randn(8, 4).astype(np.float32)
        sharded = jax.device_put(jnp.asarray(full),
                                 NamedSharding(mesh, P("data")))
        d = str(tmp_path / "ck2")
        ckpt.save_sharded(d, {"W": sharded})
        # replicated target: the full array assembles from the 8 shards
        repl = jax.device_put(jnp.zeros((8, 4)), NamedSharding(mesh, P()))
        restored, _ = ckpt.load_sharded(d, {"W": repl})
        np.testing.assert_array_equal(np.asarray(restored["W"]), full)
        # 4-device shrunk mesh: each wider shard stitches from two saved
        half = Mesh(np.array(jax.devices()[:4]).reshape(4), ("data",))
        tgt = jax.device_put(jnp.zeros((8, 4)), NamedSharding(half, P("data")))
        restored, _ = ckpt.load_sharded(d, {"W": tgt})
        np.testing.assert_array_equal(np.asarray(restored["W"]), full)
        assert len(restored["W"].sharding.device_set) == 4

    def test_uncoverable_topology_still_fails_loudly(self, tmp_path):
        # shards that genuinely can't tile the requested slice (a shard
        # missing from the manifest) must raise, never return garbage
        import jax
        import jax.numpy as jnp
        import json
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel import checkpoint as ckpt

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        sharded = jax.device_put(jnp.zeros((8, 4)),
                                 NamedSharding(mesh, P("data")))
        d = str(tmp_path / "ck4")
        ckpt.save_sharded(d, {"W": sharded})
        man = os.path.join(d, "manifest.json")
        with open(man) as f:
            manifest = json.load(f)
        manifest["leaves"]["W"]["shards"].pop("0:1;0:4")
        with open(man, "w") as f:
            json.dump(manifest, f)
        repl = jax.device_put(jnp.zeros((8, 4)), NamedSharding(mesh, P()))
        with pytest.raises(FileNotFoundError, match="cover only"):
            ckpt.load_sharded(d, {"W": repl})


# ===================================================== socket coordinator
# ISSUE 15 tier 3: the PR-6 barrier protocol over real OS processes.
# Workers are jax-free on purpose — the coordinator is pure wire
# protocol, and jax-free workers keep the socket tests well under the
# 30 s budget the tier-1 gate expects.

_BARRIER_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["DL4J_REPO"])
from deeplearning4j_tpu.distributed import SocketCoordinator

rank = os.environ["COORD_RANK"]
addr = os.environ["COORD_ADDR"]
steps = json.loads(os.environ["COORD_STEPS"])
c = SocketCoordinator(addr, participant=f"p{rank}",
                      heartbeat_interval=0.2)
agreed = [c.resume_barrier(f"p{rank}", s, timeout=20.0) for s in steps]
c.close()
print("RESULT " + json.dumps({"rank": rank, "agreed": agreed}))
"""

_DEAD_PEER_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["DL4J_REPO"])
from deeplearning4j_tpu.distributed import DeadPeerError, SocketCoordinator

c = SocketCoordinator(os.environ["COORD_ADDR"], participant="alive",
                      heartbeat_interval=0.2)
try:
    c.resume_barrier("alive", 5, timeout=20.0)
    out = {"error": None}
except DeadPeerError as e:
    out = {"error": "dead_peer", "peer": e.peer,
           "generation": e.generation}
c.close()
print("RESULT " + json.dumps(out))
"""

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(script_path, extra_env):
    env = dict(os.environ)
    env["DL4J_REPO"] = _REPO
    env.update(extra_env)
    return subprocess.Popen([sys.executable, script_path],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, env=env, text=True)


def _result(proc, timeout=60):
    out, _ = proc.communicate(timeout=timeout)
    assert proc.returncode == 0, f"worker failed:\n{out[-2000:]}"
    line = [l for l in out.splitlines() if l.startswith("RESULT ")][-1]
    return json.loads(line[len("RESULT "):])


@pytest.mark.multihost
class TestSocketCoordinatorMultiProcess:
    def test_two_process_barrier_agrees_with_in_process(self, tmp_path):
        """THE tier-3 pin: 2 OS worker processes run two successive
        resume barriers over the socket coordinator and agree on
        exactly the steps the in-process coordinator agrees on for the
        same inputs (min per round; barriers reusable)."""
        from deeplearning4j_tpu.distributed import SocketCoordinatorServer
        from deeplearning4j_tpu.parallel.elastic import InProcessCoordinator

        steps = {"0": [12, 20], "1": [7, 25]}
        # in-process reference for the same arrival steps
        ref = InProcessCoordinator(2)
        ref_agreed = {r: [] for r in steps}

        def arrive(rank):
            for s in steps[rank]:
                ref_agreed[rank].append(
                    ref.resume_barrier(f"p{rank}", s, timeout=10.0))
        ts = [threading.Thread(target=arrive, args=(r,)) for r in steps]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

        worker = str(tmp_path / "worker.py")
        with open(worker, "w") as f:
            f.write(_BARRIER_WORKER)
        with SocketCoordinatorServer(participants=2) as srv:
            procs = [_spawn(worker, {"COORD_RANK": r,
                                     "COORD_ADDR": srv.address,
                                     "COORD_STEPS": json.dumps(steps[r])})
                     for r in steps]
            results = {res["rank"]: res["agreed"]
                       for res in (_result(p) for p in procs)}
        assert results == ref_agreed == {"0": [7, 20], "1": [7, 20]}

    def test_dead_peer_surfaces_structured_error(self, tmp_path):
        """A registered peer that stops heartbeating while a barrier is
        pending fails the round for the survivor with DeadPeerError
        (peer name + generation), not a bare timeout."""
        from deeplearning4j_tpu.distributed import (SocketCoordinator,
                                                    SocketCoordinatorServer)
        worker = str(tmp_path / "worker.py")
        with open(worker, "w") as f:
            f.write(_DEAD_PEER_WORKER)
        with SocketCoordinatorServer(participants=2,
                                     heartbeat_timeout=0.6) as srv:
            # the doomed peer registers, then dies (heartbeats stop)
            doomed = SocketCoordinator(srv.address, participant="doomed",
                                       heartbeat_interval=0.2)
            doomed.hello()
            doomed.close()
            res = _result(_spawn(worker, {"COORD_ADDR": srv.address}))
        assert res == {"error": "dead_peer", "peer": "doomed",
                       "generation": 0}

    def test_coord_peer_death_fault_kind(self):
        """The faults.py seam: a FaultPlan-planned peer death fires the
        dead-peer path deterministically even while the peer's process
        keeps heartbeating — every barrier failure mode is a seeded
        chaos test, per the resilience-stack contract."""
        from deeplearning4j_tpu.distributed import (DeadPeerError,
                                                    SocketCoordinator,
                                                    SocketCoordinatorServer)
        from deeplearning4j_tpu.faults import FaultPlan
        plan = FaultPlan(coord_peer_death={"participant": "zombie",
                                           "generation": 0})
        with SocketCoordinatorServer(participants=2, heartbeat_timeout=0.5,
                                     plan=plan) as srv:
            zombie = SocketCoordinator(srv.address, participant="zombie",
                                       heartbeat_interval=0.1)
            zombie.hello()          # keeps heartbeating, but planned dead
            alive = SocketCoordinator(srv.address, participant="alive")
            with pytest.raises(DeadPeerError) as ei:
                alive.resume_barrier("alive", 3, timeout=10.0)
            assert ei.value.peer == "zombie"
            zombie.close()
            alive.close()

    def test_barrier_timeout_when_peer_never_registers(self):
        from deeplearning4j_tpu.distributed import (SocketCoordinator,
                                                    SocketCoordinatorServer)
        with SocketCoordinatorServer(participants=2) as srv:
            c = SocketCoordinator(srv.address, participant="alone")
            with pytest.raises(TimeoutError, match="1/2 participants"):
                c.resume_barrier("alone", 4, timeout=0.4)
            c.close()


@pytest.mark.multihost
class TestFileCoordinator:
    def test_two_process_file_barrier(self, tmp_path):
        """Shared-filesystem rendezvous: 2 OS processes agree on the min
        step with no server process at all."""
        script = str(tmp_path / "fworker.py")
        with open(script, "w") as f:
            f.write(r"""
import json, os, sys
sys.path.insert(0, os.environ["DL4J_REPO"])
from deeplearning4j_tpu.distributed import FileCoordinator
c = FileCoordinator(os.environ["COORD_DIR"], participants=2,
                    participant=os.environ["COORD_RANK"])
agreed = c.resume_barrier(os.environ["COORD_RANK"],
                          int(os.environ["COORD_STEP"]), timeout=20.0)
c.close()
print("RESULT " + json.dumps({"agreed": agreed}))
""")
        d = str(tmp_path / "coord")
        procs = [_spawn(script, {"COORD_DIR": d, "COORD_RANK": f"p{i}",
                                 "COORD_STEP": str(s)})
                 for i, s in enumerate((9, 4))]
        results = [_result(p) for p in procs]
        assert [r["agreed"] for r in results] == [4, 4]

    def test_file_dead_peer(self, tmp_path):
        from deeplearning4j_tpu.distributed import (DeadPeerError,
                                                    FileCoordinator)
        d = str(tmp_path / "coord2")
        dead = FileCoordinator(d, participants=2, participant="dead",
                               heartbeat_timeout=0.5,
                               heartbeat_interval=0.1)
        # simulate a CRASH (not a clean close, which retires the
        # heartbeat file): the heartbeat thread just stops
        dead._closed.set()
        dead._hb_thread.join(timeout=2.0)
        alive = FileCoordinator(d, participants=2, participant="alive",
                                heartbeat_timeout=0.5)
        with pytest.raises(DeadPeerError) as ei:
            alive.resume_barrier("alive", 3, timeout=10.0)
        assert ei.value.peer == "dead"
        alive.close()

    def test_reused_directory_ignores_previous_runs_files(self, tmp_path):
        """A coordination directory reused after a crash must not agree
        on the previous run's steps (stale gen files) or flag its dead
        participants (stale hb files) — freshness-floored by mtime."""
        import time as _time
        from deeplearning4j_tpu.distributed import FileCoordinator
        d = str(tmp_path / "coord3")
        os.makedirs(d)
        past = _time.time() - 60
        for fname in ("gen0_ghost.json", "hb_ghost"):
            path = os.path.join(d, fname)
            with open(path, "w") as f:
                f.write('{"step": 1}')
            os.utime(path, (past, past))
        results = {}

        def arrive(name, step):
            c = FileCoordinator(d, participants=2, participant=name,
                                heartbeat_timeout=5.0)
            results[name] = c.resume_barrier(name, step, timeout=10.0)
            c.close()
        ts = [threading.Thread(target=arrive, args=(n, s))
              for n, s in (("a", 9), ("b", 6))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # the ghost's stale step-1 arrival did NOT join the round
        assert results == {"a": 6, "b": 6}

    def test_quick_restart_ignores_previous_runs_result(self, tmp_path):
        """A supervisor restarting a worker into a reused directory
        within seconds must NOT consume the dead run's result file:
        acceptance is floored on this run's own arrival mtime, not on
        construction time."""
        from deeplearning4j_tpu.distributed import FileCoordinator
        d = str(tmp_path / "coord5")
        os.makedirs(d)
        with open(os.path.join(d, "result_gen0.json"), "w") as f:
            f.write('{"step": 999}')        # written moments ago
        c = FileCoordinator(d, participants=2, participant="a")
        with pytest.raises(TimeoutError):
            c.resume_barrier("a", 5, timeout=1.0)
        c.close()

    def test_staggered_construction_still_agrees(self, tmp_path):
        """A peer that constructs (and arrives) seconds before another
        even builds its coordinator must still be counted — liveness is
        heartbeat freshness, not file age vs construction time."""
        import time as _time
        from deeplearning4j_tpu.distributed import FileCoordinator
        d = str(tmp_path / "coord4")
        results = {}
        early = FileCoordinator(d, participants=2, participant="early",
                                heartbeat_interval=0.2)

        def arrive_early():
            results["early"] = early.resume_barrier("early", 11,
                                                    timeout=20.0)
        t = threading.Thread(target=arrive_early)
        t.start()
        _time.sleep(1.5)        # "early" has long since arrived
        late = FileCoordinator(d, participants=2, participant="late",
                               heartbeat_interval=0.2)
        results["late"] = late.resume_barrier("late", 4, timeout=20.0)
        t.join()
        early.close()
        late.close()
        assert results == {"early": 4, "late": 4}


@pytest.mark.multihost
class TestElasticOverSocketCoordinator:
    def test_fit_elastic_shrinks_through_the_socket_barrier(self, tmp_path,
                                                            devices):
        """``ParallelWrapper.fit(elastic=...)`` with the SOCKET
        coordinator plugged into ElasticConfig: a device loss runs the
        coordinated shrink with the resume barrier over TCP — the
        in-process stand-in is genuinely replaced, fit completes on the
        survivor mesh."""
        from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
        from deeplearning4j_tpu.distributed import (SocketCoordinator,
                                                    SocketCoordinatorServer)
        from deeplearning4j_tpu.faults import FaultPlan
        from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.parallel import ParallelWrapper
        from deeplearning4j_tpu.parallel.elastic import ElasticConfig
        from deeplearning4j_tpu.train import updaters
        from deeplearning4j_tpu.train.resilience import CheckpointConfig

        conf = (NeuralNetConfiguration.Builder().seed(3)
                .updater(updaters.Sgd(0.05)).list()
                .layer(DenseLayer(nOut=16, activation="relu"))
                .layer(OutputLayer(nOut=2, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(0)
        ds = DataSet(rng.randn(64, 8).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.randint(0, 2, 64)])
        plan = FaultPlan(device_loss_at_step=3, lose_devices=[6, 7])
        with SocketCoordinatorServer(participants=1) as srv:
            coord = SocketCoordinator(srv.address, participant="proc0")
            w = ParallelWrapper(net)
            w.fit(ListDataSetIterator(ds, 8), epochs=1,
                  checkpoint=CheckpointConfig(str(tmp_path / "ck")),
                  elastic=ElasticConfig(coordinator=coord),
                  faults=plan)
            coord.close()
        assert w.mesh.size("data") == 6
        assert net._iteration == 8
        assert np.isfinite(float(net.score()))
