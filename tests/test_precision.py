"""PrecisionPolicy seam + nonfinite-provenance sanitizer (ISSUE 11):
bf16/fp16 policy fits (loss parity, zero steady-state recompiles, loss
scaling), per-layer dtype overrides, and first-nonfinite attribution
(layer/op/step) through batches, FaultPlan layer poisons, megasteps,
and graphs."""

import numpy as np
import pytest

from deeplearning4j_tpu import profiler
from deeplearning4j_tpu.analysis.churn import get_churn_detector
from deeplearning4j_tpu.data.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.faults import FaultPlan
from deeplearning4j_tpu.nn.config import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.precision import (PrecisionPolicy, normalize_dtype,
                                             runtime_check)
from deeplearning4j_tpu.profiler.modes import ProfilingMode
from deeplearning4j_tpu.profiler.sanitizer import (NonfiniteAttributionError,
                                                   track_value_ranges)
from deeplearning4j_tpu.train.updaters import Adam


def _mlp_conf(seed=7, hidden=16):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(0.01))
            .weightInit("xavier").list()
            .layer(DenseLayer(nOut=hidden, activation="relu"))
            .layer(DenseLayer(nOut=hidden, activation="relu"))
            .layer(OutputLayer(nOut=3, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(8))
            .build())


def _graph_conf(seed=7):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(0.01))
            .graphBuilder()
            .addInputs("in")
            .setInputTypes(InputType.feedForward(8))
            .addLayer("fc", DenseLayer(nOut=16, activation="relu"), "in")
            .addLayer("out", OutputLayer(nOut=3, lossFunction="mcxent",
                                         activation="softmax"), "fc")
            .setOutputs("out")
            .build())


def _data(n=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = np.zeros((n, 3), np.float32)
    y[np.arange(n), rng.randint(0, 3, n)] = 1.0
    return x, y


@pytest.fixture(autouse=True)
def _mode_reset():
    yield
    profiler.set_profiling_mode(None)
    track_value_ranges(False)


# ----------------------------------------------------------- the policy
class TestPrecisionPolicy:
    def test_coerce_and_aliases(self):
        p = PrecisionPolicy.coerce("bf16")
        assert p.compute == "bfloat16" and p.params == "float32"
        assert PrecisionPolicy.coerce(None) is None
        assert PrecisionPolicy.coerce(p) is p
        assert normalize_dtype("FP16") == "float16"
        with pytest.raises(ValueError):
            normalize_dtype("float8")
        with pytest.raises(TypeError):
            PrecisionPolicy.coerce(42)

    def test_signature_and_eq(self):
        a = PrecisionPolicy("bfloat16")
        b = PrecisionPolicy("bf16")
        assert a == b and a.signature() == b.signature()
        assert a != PrecisionPolicy("bfloat16", loss_scale=8.0)

    def test_config_roundtrip(self):
        p = PrecisionPolicy("float16", loss_scale=2 ** 15)
        assert PrecisionPolicy.from_config(p.to_config()) == p

    def test_runtime_rejects_low_precision_masters(self):
        with pytest.raises(ValueError, match="E301"):
            runtime_check(PrecisionPolicy("bfloat16", params="bfloat16"))
        net = MultiLayerNetwork(_mlp_conf()).init()
        with pytest.raises(ValueError, match="master params"):
            net.setPrecisionPolicy(PrecisionPolicy("float16",
                                                   params="float16"))

    def test_invalid_loss_scale(self):
        with pytest.raises(ValueError, match="positive"):
            PrecisionPolicy("float16", loss_scale=0)


class TestPolicyFit:
    def test_bf16_loss_parity_vs_fp32(self):
        x, y = _data()
        net32 = MultiLayerNetwork(_mlp_conf()).init()
        net32.fit(x, y, epochs=5)
        netbf = MultiLayerNetwork(_mlp_conf()).init()
        netbf.fit(x, y, epochs=5, precision="bf16")
        l32, lbf = net32.score(), netbf.score()
        assert np.isfinite(lbf)
        assert abs(l32 - lbf) / abs(l32) < 0.05, (l32, lbf)
        # master params stay fp32 under the policy
        assert str(netbf._params[0]["W"].dtype) == "float32"

    def test_fp16_with_loss_scale_tracks_fp32(self):
        x, y = _data()
        net32 = MultiLayerNetwork(_mlp_conf()).init()
        net32.fit(x, y, epochs=5)
        net16 = MultiLayerNetwork(_mlp_conf()).init()
        net16.fit(x, y, epochs=5,
                  precision=PrecisionPolicy("float16", loss_scale=1024.0))
        # the reported loss is UNSCALED (listeners see the true loss)
        assert abs(net32.score() - net16.score()) / abs(net32.score()) < 0.1

    def test_loss_scale_is_numerically_neutral_in_fp32(self):
        """Scale-then-unscale must be exact in fp32: same updates."""
        x, y = _data()
        a = MultiLayerNetwork(_mlp_conf()).init()
        a.fit(x, y, epochs=3)
        b = MultiLayerNetwork(_mlp_conf()).init()
        b.fit(x, y, epochs=3,
              precision=PrecisionPolicy("float32", loss_scale=4.0))
        np.testing.assert_allclose(np.asarray(a.params()),
                                   np.asarray(b.params()), rtol=1e-5)

    def test_zero_steady_state_recompiles(self):
        det = get_churn_detector()
        x, y = _data()
        it = ListDataSetIterator(DataSet(x, y), batch_size=8)
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.setPrecisionPolicy("bf16")
        net.fit(it, epochs=3)
        # ONE jit signature at the fit site across 4 batches x 3 epochs:
        # the policy keys the cache, it does not churn it
        assert det.signature_count("MultiLayerNetwork.fit", owner=net) == 1
        assert not det.diagnostics_for(net)
        # re-attaching an EQUAL policy keeps the compiled cache
        cache = dict(net._train_step_cache)
        net.setPrecisionPolicy(PrecisionPolicy("bfloat16"))
        assert net._train_step_cache == cache
        # a DIFFERENT policy busts it (one clean recompile)
        net.setPrecisionPolicy(None)
        assert net._train_step_cache == {}

    def test_per_layer_fp32_island_runs(self):
        x, y = _data()
        conf = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(0.01))
                .list()
                .layer(DenseLayer(nOut=16, activation="relu"))
                .layer(DenseLayer(nOut=16, activation="relu",
                                  dataType="float32"))
                .layer(OutputLayer(nOut=3, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(8)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(x, y, epochs=2, precision="bf16")
        assert np.isfinite(net.score())

    def test_graph_bf16_parity(self):
        x, y = _data()
        g32 = ComputationGraph(_graph_conf()).init()
        g32.fit(x, y, epochs=5)
        gbf = ComputationGraph(_graph_conf()).init()
        gbf.fit(x, y, epochs=5, precision="bf16")
        assert abs(g32.score() - gbf.score()) / abs(g32.score()) < 0.05

    def test_megastep_policy_matches_single_step(self):
        x, y = _data()
        a = MultiLayerNetwork(_mlp_conf()).init()
        a.fit(ListDataSetIterator(DataSet(x, y), batch_size=8), epochs=2,
              precision="bf16")
        b = MultiLayerNetwork(_mlp_conf()).init()
        b.fit(ListDataSetIterator(DataSet(x, y), batch_size=8), epochs=2,
              steps_per_dispatch=2, prefetch=0, precision="bf16")
        np.testing.assert_allclose(np.asarray(a.params(), np.float32),
                                   np.asarray(b.params(), np.float32),
                                   rtol=2e-2, atol=1e-3)

    def test_layer_datatype_config_roundtrip(self):
        from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
        conf = (NeuralNetConfiguration.Builder().list()
                .layer(DenseLayer(nOut=16, dataType="fp32"))
                .layer(OutputLayer(nOut=2))
                .setInputType(InputType.feedForward(4)).build())
        assert conf.layers[0].dtype_override == "float32"
        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert back.layers[0].dtype_override == "float32"


# ----------------------------------------------------- provenance pins
class TestNonfiniteProvenance:
    def test_nan_batch_attributed_to_input(self):
        x, y = _data()
        x[3, 1] = np.nan
        net = MultiLayerNetwork(_mlp_conf()).init()
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        with pytest.raises(NonfiniteAttributionError,
                           match="NAN_PANIC") as ei:
            net.fit(x, y, epochs=1)
        assert ei.value.layer == "<input>" and ei.value.op == "batch"
        assert ei.value.step == 1

    def test_faultplan_layer_poison_attributed_to_exact_layer(self):
        """THE pin: NaN injected at layer k via FaultPlan is attributed
        to layer k / op params / the planned step — not to the loss."""
        x, y = _data()
        net = MultiLayerNetwork(_mlp_conf()).init()
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        with pytest.raises(NonfiniteAttributionError) as ei:
            net.fit(x, y, epochs=3,
                    faults=FaultPlan(nan_layer_params_at={2: 1}))
        assert ei.value.layer == "1:DenseLayer", ei.value.layer
        assert ei.value.op == "params"
        assert ei.value.step == 2
        # and the info metric names the same site
        g = profiler.get_registry().get("dl4j_nonfinite_first_site")
        children = g.children()
        assert ("MultiLayerNetwork", "1:DenseLayer", "params") in children
        assert children[("MultiLayerNetwork", "1:DenseLayer",
                         "params")].value == 2

    def test_megastep_attribution_names_mid_dispatch_step(self):
        x, y = _data()
        x[17, 2] = np.nan                      # 3rd batch of 8 -> step 3
        net = MultiLayerNetwork(_mlp_conf()).init()
        it = ListDataSetIterator(DataSet(x, y), batch_size=8)
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        with pytest.raises(NonfiniteAttributionError) as ei:
            net.fit(it, epochs=1, steps_per_dispatch=2, prefetch=0)
        assert ei.value.step == 3
        assert ei.value.layer == "<input>"

    def test_graph_poison_attributed_to_named_layer(self):
        x, y = _data()
        g = ComputationGraph(_graph_conf()).init()
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        with pytest.raises(NonfiniteAttributionError) as ei:
            g.fit(x, y, epochs=3,
                  faults=FaultPlan(nan_layer_params_at={2: "fc"}))
        assert ei.value.layer == "fc" and ei.value.op == "params"
        assert ei.value.step == 2

    def test_attribution_exact_beyond_snapshot_interval(self):
        """The amortized snapshot window (default: copy every 8
        dispatches) still attributes exactly: a poisoned batch at step
        12 replays through the rolled-forward snapshot."""
        x, y = _data(n=8)
        net = MultiLayerNetwork(_mlp_conf()).init()
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        batches = [DataSet(x, y) for _ in range(11)]
        xb = x.copy()
        xb[0, 0] = np.nan
        batches.append(DataSet(xb, y))
        with pytest.raises(NonfiniteAttributionError) as ei:
            net.fit(batches, epochs=1)
        assert ei.value.step == 12
        assert ei.value.layer == "<input>" and ei.value.op == "batch"

    def test_off_mode_pays_nothing_and_raises_nothing(self):
        x, y = _data()
        x[0, 0] = np.nan
        net = MultiLayerNetwork(_mlp_conf()).init()
        before = profiler.get_registry().get(
            "dl4j_nonfinite_panics_total").value
        net.fit(x, y, epochs=1)                # no panic mode: no raise
        assert profiler.get_registry().get(
            "dl4j_nonfinite_panics_total").value == before

    def test_inf_panic_mode_attributes_inf(self):
        """INF_PANIC keeps its legacy inf-only loss gate — an overflowed
        MSE loss (1e30^2 -> inf in fp32) is caught and attributed."""
        from deeplearning4j_tpu.nn.layers import LossLayer
        conf = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(0.01))
                .list()
                .layer(DenseLayer(nOut=8, activation="identity"))
                .layer(LossLayer(lossFunction="mse"))
                .setInputType(InputType.feedForward(8)).build())
        x = np.full((4, 8), 1e30, np.float32)
        y = np.zeros((4, 8), np.float32)
        net = MultiLayerNetwork(conf).init()
        profiler.set_profiling_mode(ProfilingMode.INF_PANIC)
        with pytest.raises(NonfiniteAttributionError, match="INF_PANIC"):
            net.fit(x, y, epochs=1)

    def test_absmax_tracking_records_ranges_and_proximity(self):
        x, y = _data()
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.setPrecisionPolicy("bf16")
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        track_value_ranges(True, every=1)
        net.fit(x, y, epochs=2)
        hist = profiler.get_registry().get("dl4j_tensor_absmax")
        layers = {k[1] for k in hist.children()}
        assert any(l.startswith("0:") for l in layers), layers
        prox = profiler.get_registry().get("dl4j_overflow_proximity")
        assert 0.0 < prox.value < 1.0           # bf16 run, sane activations
    def test_nan_panic_keeps_nan_only_loss_gate(self):
        """Review regression: NAN_PANIC's loss gate stays NaN-only
        (legacy panic_check semantics) — an inf loss passes under
        NAN_PANIC and raises under INF_PANIC."""
        from deeplearning4j_tpu.nn.layers import LossLayer
        conf = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(0.01))
                .list()
                .layer(DenseLayer(nOut=8, activation="identity"))
                .layer(LossLayer(lossFunction="mse"))
                .setInputType(InputType.feedForward(8)).build())
        x = np.full((4, 8), 1e30, np.float32)   # mse -> inf, not NaN
        y = np.zeros((4, 8), np.float32)
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        MultiLayerNetwork(conf).init().fit(x, y, epochs=1)   # no raise

    def test_nn_package_lazy_attributes(self):
        """Review regression: the PEP-562 nn/__init__ still exposes the
        submodule attributes the eager imports used to set."""
        import deeplearning4j_tpu.nn as nn_pkg
        assert nn_pkg.multilayer.MultiLayerNetwork is MultiLayerNetwork
        assert hasattr(nn_pkg.graph, "ComputationGraph")
        assert hasattr(nn_pkg.layers, "DenseLayer")
        assert nn_pkg.PrecisionPolicy is PrecisionPolicy

    def _tbptt_net(self, seed=7):
        from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
        conf = (NeuralNetConfiguration.Builder().seed(seed)
                .updater(Adam(0.01)).weightInit("xavier").list()
                .layer(LSTM(nOut=8, activation="tanh"))
                .layer(RnnOutputLayer(nOut=2, lossFunction="mcxent",
                                      activation="softmax"))
                .setInputType(InputType.recurrent(4, 8))
                .backpropType("tbptt", tbpttLength=4).build())
        return MultiLayerNetwork(conf).init()

    def _tbptt_data(self, n=4, seed=0):
        rng = np.random.RandomState(seed)
        x = rng.randn(n, 4, 8).astype(np.float32)
        y = np.zeros((n, 2, 8), np.float32)
        y[np.arange(n), rng.randint(0, 2, n), :] = 1.0
        return x, y

    def test_tbptt_honors_precision_policy(self):
        """ISSUE 20 satellite (PR 11 carry closed): the compiled TBPTT
        step honors the attached PrecisionPolicy — policy_cast + loss
        scaling per segment, no warning, bf16 loss parity vs fp32."""
        import warnings as _w
        x, y = self._tbptt_data()
        n32 = self._tbptt_net()
        n32.fit(x, y, epochs=3)
        nbf = self._tbptt_net()
        with _w.catch_warnings():
            _w.simplefilter("error")        # the old warning is GONE
            nbf.fit(x, y, epochs=3, precision="bf16")
        l32, lbf = float(n32.score()), float(nbf.score())
        assert np.isfinite(lbf)
        assert abs(l32 - lbf) / abs(l32) < 0.05, (l32, lbf)
        # master params stay fp32 under the policy
        assert str(nbf._params[0]["W"].dtype) == "float32"
        # fp16 static loss scaling survives the segment backward too
        n16 = self._tbptt_net()
        n16.fit(x, y, epochs=3,
                precision=PrecisionPolicy("float16", loss_scale=1024.0))
        assert abs(l32 - float(n16.score())) / abs(l32) < 0.15

    def test_tbptt_policy_zero_steady_state_recompiles(self):
        """The policy keys the TBPTT step cache, it does not churn it:
        exactly two signatures (carried-state pytree None -> materialized
        on each batch's first segment) however many epochs run."""
        from deeplearning4j_tpu.analysis.churn import get_churn_detector
        det = get_churn_detector()
        x, y = self._tbptt_data()
        net = self._tbptt_net()
        net.setPrecisionPolicy("bf16")
        net.fit(x, y, epochs=2)
        after_warm = det.signature_count("MultiLayerNetwork.tbptt",
                                         owner=net)
        assert after_warm == 2, after_warm
        net.fit(x, y, epochs=3)
        assert det.signature_count("MultiLayerNetwork.tbptt",
                                   owner=net) == after_warm
        assert not det.diagnostics_for(net)

    def test_mid_dispatch_poison_fires_at_next_boundary(self):
        """Review regression: a poison planned for a mid-megastep step
        lands at the first dispatch boundary at or after it instead of
        silently never firing."""
        x, y = _data()
        net = MultiLayerNetwork(_mlp_conf()).init()
        it = ListDataSetIterator(DataSet(x, y), batch_size=8)
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        with pytest.raises(NonfiniteAttributionError) as ei:
            # K=2: boundaries at steps 1, 3, 5... — a step-2 plan fires
            # at the step-3 boundary
            net.fit(it, epochs=2, steps_per_dispatch=2, prefetch=0,
                    faults=FaultPlan(nan_layer_params_at={2: 1}))
        assert ei.value.layer == "1:DenseLayer" and ei.value.op == "params"
        assert ei.value.step == 3


class TestTbpttProvenance:
    """ISSUE 18 satellite: first-nonfinite attribution through the TBPTT
    window — the one fit path ISSUE 11 left on the coarse panic_check."""

    def _net(self, seed=7):
        from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
        conf = (NeuralNetConfiguration.Builder().seed(seed)
                .updater(Adam(0.01)).weightInit("xavier").list()
                .layer(LSTM(nOut=6, activation="tanh"))
                .layer(RnnOutputLayer(nOut=2, lossFunction="mcxent",
                                      activation="softmax"))
                .setInputType(InputType.recurrent(3, 12))
                .backpropType("tbptt", 4)
                .build())
        return MultiLayerNetwork(conf).init()

    def _seq_data(self, n=5, seed=0):
        rng = np.random.RandomState(seed)
        x = rng.randn(n, 3, 12).astype(np.float32)
        y = np.zeros((n, 2, 12), np.float32)
        y[np.arange(n), rng.randint(0, 2, n), :] = 1.0
        return x, y

    def test_tbptt_faultplan_poison_attributed_through_window(self):
        """Poison at step 4 = second batch, after a full window of
        segment dispatches — the replay must roll carried state through
        the ring and still name the exact layer/op/step."""
        x, y = self._seq_data()
        net = self._net()
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        with pytest.raises(NonfiniteAttributionError) as ei:
            net.fit(DataSet(x, y), epochs=2,
                    faults=FaultPlan(nan_layer_params_at={4: 0}))
        assert ei.value.layer == "0:LSTM", ei.value.layer
        assert ei.value.op == "params"
        assert ei.value.step == 4
        g = profiler.get_registry().get("dl4j_nonfinite_first_site")
        assert ("MultiLayerNetwork", "0:LSTM",
                "params") in g.children()

    def test_tbptt_nan_input_attributed_to_batch(self):
        x, y = self._seq_data()
        x[1, 2, 5] = np.nan
        net = self._net()
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        with pytest.raises(NonfiniteAttributionError) as ei:
            net.fit(DataSet(x, y), epochs=1)
        assert ei.value.layer == "<input>" and ei.value.op == "batch"

    def test_tbptt_clean_fit_unchanged(self):
        x, y = self._seq_data()
        net = self._net()
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        net.fit(DataSet(x, y), epochs=2)   # must not raise
