"""OpValidation suite — the registry-wide validation + coverage gate.

Reference parity: ``org.nd4j.autodiff.validation.OpValidation`` +
``OpValidationSuite``'s coverage check (SURVEY.md §4 "Op validation (the
centerpiece)"): every registered op must be exercised (forward vs golden
where one exists, FD gradcheck for differentiable ops) or carry an
explicit exemption with a pointer — adding an op without validation FAILS
this suite.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.ops import registry as R
from deeplearning4j_tpu.ops import validation as V

_CASES = V.all_cases()
_BY_ID = [f"{c.op}" for c in _CASES]


@pytest.mark.parametrize("case", _CASES, ids=_BY_ID)
def test_op(case):
    V.run_case(case)


def test_coverage_gate():
    """The reference's coverage report: no registered op may be silently
    unvalidated. This FAILS when an op is added without a case."""
    rep = V.coverage_report(_CASES)
    assert not rep.uncovered, (
        f"{len(rep.uncovered)} registered ops have no validation case and "
        f"no exemption: {rep.uncovered}")
    assert rep.pct >= 95.0, f"coverage {rep.pct:.1f}% < 95%"


def test_exemptions_point_somewhere():
    for op, reason in V.EXEMPT.items():
        assert R.has(op), f"exempt op '{op}' is not even registered"
        assert len(reason) > 10, f"exemption for '{op}' has no pointer"


def test_serialization_roundtrip_of_registry_ops():
    """Registry ops recorded in a SameDiff graph survive save/load
    (the per-op serialization leg of OpValidation)."""
    from deeplearning4j_tpu.autodiff.samediff import SameDiff
    import tempfile, os
    sd = SameDiff.create()
    x = sd.placeHolder("x", shape=(2, 3), dtype=np.float32)
    h = x.add(1.0).mul(2.0)
    out = h.sub(0.5)
    sd.output({"x": np.zeros((2, 3), np.float32)}, [out.name])
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "ops.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        xv = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        a = np.asarray(sd.output({"x": xv}, [out.name])[out.name])
        b = np.asarray(sd2.output({"x": xv}, [out.name])[out.name])
        np.testing.assert_array_equal(a, b)
