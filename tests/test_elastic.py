"""Elastic multi-device training (ISSUE 6): device-loss detection,
dispatch watchdogs, coordinated mesh-shrink resume, and the robust
ParallelInference retry path — every recovery driven by a deterministic
injected fault on the 8-device virtual CPU mesh.

The acceptance pin: an 8-device ParallelWrapper fit that loses half its
devices mid-run writes a coordinated checkpoint of the last globally
completed step, shrinks the mesh, finishes — and its params equal a
FRESH 4-device fit resumed from that same checkpoint, bit-exact.
"""

import threading
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import (DataSet, DevicePrefetcher,
                                             ListDataSetIterator)
from deeplearning4j_tpu.faults import FaultPlan
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import (DeviceMesh, DispatchTimeoutError,
                                         ElasticConfig, ElasticShrinkError,
                                         InferenceFailedError,
                                         InProcessCoordinator,
                                         ParallelInference, ParallelWrapper)
from deeplearning4j_tpu.parallel.elastic import (DEVICE_LOST,
                                                 DeviceMonitor,
                                                 DispatchWatchdog,
                                                 MESH_SHRINKS,
                                                 STRAGGLER_SECONDS,
                                                 WATCHDOG_TIMEOUTS)
from deeplearning4j_tpu.parallel.wrapper import _INFERENCE_REPLICA_FAILURES
from deeplearning4j_tpu.train import updaters
from deeplearning4j_tpu.train.resilience import (CheckpointConfig,
                                                 CheckpointManager, NanPolicy)

NIN, NOUT, BATCH, NBATCH = 6, 3, 8, 10


def mlp(seed=42, lr=0.01):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(updaters.Adam(lr)).list()
            .layer(DenseLayer(nOut=8, activation="relu"))
            .layer(OutputLayer(nOut=NOUT, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(NIN))
            .build())
    return MultiLayerNetwork(conf).init()


def iterator(seed=5):
    rng = np.random.RandomState(seed)
    x = rng.randn(NBATCH * BATCH, NIN).astype(np.float32)
    y = np.eye(NOUT, dtype=np.float32)[rng.randint(0, NOUT, NBATCH * BATCH)]
    return ListDataSetIterator(DataSet(x, y), batch_size=BATCH)


@pytest.fixture(scope="module")
def devices8():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return jax.devices()


# ============================================================ device monitor
class TestDeviceMonitor:
    def test_all_healthy(self, devices8):
        health = DeviceMonitor().probe(devices8)
        assert health.healthy() and not health.dead
        assert set(health.probe_seconds) == {d.id for d in devices8}

    def test_planned_loss_classified_dead(self, devices8):
        plan = FaultPlan(device_loss_at_step=3, lose_devices=[2, 5])
        mon = DeviceMonitor(plan=plan)
        assert mon.probe(devices8, step=2).dead == set()   # not yet
        health = mon.probe(devices8, step=3)
        assert health.dead == {2, 5}
        assert 2 not in health.probe_seconds               # dead: no probe
        # persistent: still dead later (a lost chip stays lost)
        assert mon.probe(devices8, step=9).dead == {2, 5}
        # step=None = "as of now" (inference-side probes)
        assert mon.probe(devices8).dead == {2, 5}

    def test_degraded_classification(self, devices8):
        health = DeviceMonitor(degraded_after=0.0).probe(devices8)
        # every real probe takes > 0s: all live devices read degraded
        assert health.degraded == {d.id for d in devices8}
        assert not health.dead


# ================================================================= watchdog
class TestDispatchWatchdog:
    def test_returns_result_inline_and_supervised(self):
        assert DispatchWatchdog(warmup=0).run(lambda: 41 + 1, 1) == 42
        wd = DispatchWatchdog(deadline=5.0, warmup=0)
        assert wd.run(lambda: "ok", 1) == "ok"
        assert wd.timeouts == 0

    def test_soft_timeout_records_straggler(self):
        wd = DispatchWatchdog(deadline=0.05, grace=10.0, warmup=0)
        before = (WATCHDOG_TIMEOUTS.value, STRAGGLER_SECONDS.count)
        assert wd.run(lambda: time.sleep(0.2) or "late", 7) == "late"
        assert wd.timeouts == 1 and wd.stragglers == 1
        assert WATCHDOG_TIMEOUTS.value == before[0] + 1
        assert STRAGGLER_SECONDS.count == before[1] + 1

    def test_hard_timeout_abandons_and_raises(self):
        release = threading.Event()
        wd = DispatchWatchdog(deadline=0.05, grace=0.15, warmup=0)
        with pytest.raises(DispatchTimeoutError, match="grace deadline"):
            wd.run(lambda: release.wait(10.0), 3)
        release.set()   # let the abandoned thread exit

    def test_warmup_dispatches_unsupervised(self):
        wd = DispatchWatchdog(deadline=0.05, grace=10.0, warmup=1)
        # a compile-length first dispatch must NOT be flagged...
        assert wd.run(lambda: time.sleep(0.2) or 1, 1) == 1
        assert wd.timeouts == 0
        # ...but the second one is supervised again
        wd.run(lambda: time.sleep(0.2) or 2, 2)
        assert wd.timeouts == 1
        wd.begin_attempt()      # a new mesh attempt re-arms leniency
        assert wd._lenient == 1

    def test_dispatch_error_reraised_on_caller(self):
        wd = DispatchWatchdog(deadline=5.0, warmup=0)
        with pytest.raises(ValueError, match="boom"):
            wd.run(lambda: (_ for _ in ()).throw(ValueError("boom")), 1)


# ============================================================== coordinator
class TestInProcessCoordinator:
    def test_single_participant(self):
        c = InProcessCoordinator(1)
        assert c.resume_barrier("p0", 17) == 17
        assert c.resume_barrier("p0", 23) == 23      # reusable

    def test_agreement_is_min_across_participants(self):
        c = InProcessCoordinator(3)
        results = {}

        def arrive(pid, step):
            results[pid] = c.resume_barrier(pid, step, timeout=10.0)

        threads = [threading.Thread(target=arrive, args=(f"p{i}", s))
                   for i, s in enumerate((7, 5, 6))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {"p0": 5, "p1": 5, "p2": 5}

    def test_missing_participant_times_out(self):
        c = InProcessCoordinator(2)
        with pytest.raises(TimeoutError, match="1/2 participants"):
            c.resume_barrier("alone", 4, timeout=0.1)


# ============================================================ elastic shrink
class TestElasticShrink:
    def _fit_elastic(self, d, plan, k=1, cfg=None, net=None, lr=0.01):
        net = net or mlp(lr=lr)
        w = ParallelWrapper(net)
        w.fit(iterator(), epochs=1, steps_per_dispatch=k,
              checkpoint=CheckpointConfig(d),
              elastic=cfg or ElasticConfig(), faults=plan)
        return net, w

    def test_loss_of_half_the_mesh_matches_fresh_small_fit(self, tmp_path,
                                                           devices8):
        """THE acceptance pin: 8-device fit loses 4 devices at step 5 ->
        coordinated checkpoint of step 5 -> shrink to 4 -> finish; params
        equal a fresh 4-device fit resumed from that checkpoint."""
        d = str(tmp_path / "c")
        lost_before = DEVICE_LOST.value
        shrinks_before = MESH_SHRINKS.value
        plan = FaultPlan(device_loss_at_step=5, lose_devices=[4, 5, 6, 7])
        net, w = self._fit_elastic(d, plan)
        assert net._iteration == NBATCH
        assert w.mesh.size("data") == 4
        assert {dev.id for dev in w.mesh.devices} == {0, 1, 2, 3}
        assert DEVICE_LOST.value == lost_before + 4
        assert MESH_SHRINKS.value == shrinks_before + 1
        # the coordinated checkpoint holds the last globally completed step
        mgr = CheckpointManager(CheckpointConfig(d))
        [(step, path)] = mgr.checkpoints()
        assert step == 5
        assert mgr.validate(path)["status"] == "elastic-shrink"
        # fresh 4-device run resumed from the same checkpoint: bit-exact
        ref = mlp()
        ParallelWrapper(ref, DeviceMesh.create(data=4,
                                               devices=devices8[:4])).fit(
            iterator(), epochs=1, checkpoint=CheckpointConfig(d, resume=True))
        assert ref._iteration == NBATCH
        assert np.array_equal(np.asarray(net.params()),
                              np.asarray(ref.params()))

    def test_shrink_composes_with_megasteps(self, tmp_path, devices8):
        d = str(tmp_path / "c")
        plan = FaultPlan(device_loss_at_step=4, lose_devices=[4, 5, 6, 7])
        net, w = self._fit_elastic(d, plan, k=2)
        assert net._iteration == NBATCH
        assert w.mesh.size("data") == 4
        ref = mlp()
        ParallelWrapper(ref, DeviceMesh.create(data=4,
                                               devices=devices8[:4])).fit(
            iterator(), epochs=1, steps_per_dispatch=2,
            checkpoint=CheckpointConfig(d, resume=True))
        assert np.array_equal(np.asarray(net.params()),
                              np.asarray(ref.params()))

    def test_hard_hang_with_device_loss_shrinks(self, tmp_path):
        # dispatch 6 hangs forever AND devices 6/7 are dead: the watchdog
        # abandons it, the probe confirms the loss, the mesh shrinks, and
        # batch 6 replays from the step-5 checkpoint
        d = str(tmp_path / "c")
        plan = FaultPlan(hung_dispatch_at=[6], hang_seconds=None,
                         device_loss_at_step=6, lose_devices=[6, 7])
        net, w = self._fit_elastic(
            d, plan, cfg=ElasticConfig(watchdog_deadline=0.1,
                                       watchdog_grace=0.3))
        assert net._iteration == NBATCH
        assert w.mesh.size("data") == 6
        mgr = CheckpointManager(CheckpointConfig(d))
        assert [s for s, _ in mgr.checkpoints()] == [5]

    def test_soft_hang_is_a_straggler_not_a_failure(self, tmp_path):
        d = str(tmp_path / "c")
        before = WATCHDOG_TIMEOUTS.value
        plan = FaultPlan(hung_dispatch_at=[4], hang_seconds=0.5)
        net, w = self._fit_elastic(
            d, plan, cfg=ElasticConfig(watchdog_deadline=0.1,
                                       watchdog_grace=30.0))
        assert net._iteration == NBATCH
        assert w.mesh.size("data") == 8             # no shrink
        assert WATCHDOG_TIMEOUTS.value == before + 1
        # the stall changed nothing numerically
        ref = mlp()
        ParallelWrapper(ref).fit(iterator(), epochs=1,
                                 checkpoint=CheckpointConfig(d + "x"))
        assert np.array_equal(np.asarray(net.params()),
                              np.asarray(ref.params()))

    def test_slow_replica_recorded_as_straggler(self, tmp_path):
        d = str(tmp_path / "c")
        before = STRAGGLER_SECONDS.count
        plan = FaultPlan(slow_replica_at=[5], slow_seconds=0.3)
        net, _ = self._fit_elastic(
            d, plan, cfg=ElasticConfig(watchdog_deadline=0.1,
                                       watchdog_grace=30.0))
        assert net._iteration == NBATCH
        assert STRAGGLER_SECONDS.count == before + 1

    def test_hard_hang_on_healthy_mesh_surfaces(self, tmp_path):
        # no dead device behind the hang: retrying could double-apply the
        # maybe-landed step, so the timeout must surface instead
        d = str(tmp_path / "c")
        net = mlp()
        with pytest.raises(DispatchTimeoutError):
            ParallelWrapper(net).fit(
                iterator(), epochs=1, checkpoint=CheckpointConfig(d),
                elastic=ElasticConfig(watchdog_deadline=0.1,
                                      watchdog_grace=0.3),
                faults=FaultPlan(hung_dispatch_at=[4], hang_seconds=None))

    def test_elastic_requires_checkpoint(self):
        with pytest.raises(ValueError, match="requires checkpoint"):
            ParallelWrapper(mlp()).fit(iterator(), elastic=ElasticConfig())

    def test_too_few_survivors_raises(self, tmp_path):
        d = str(tmp_path / "c")
        plan = FaultPlan(device_loss_at_step=3,
                         lose_devices=[1, 2, 3, 4, 5, 6, 7])
        with pytest.raises(ElasticShrinkError, match="min_devices"):
            self._fit_elastic(d, plan, cfg=ElasticConfig(min_devices=2))

    def test_lr_policy_linear_rescales(self, tmp_path):
        d = str(tmp_path / "c")
        plan = FaultPlan(device_loss_at_step=5, lose_devices=[4, 5, 6, 7])
        net, _ = self._fit_elastic(d, plan,
                                   cfg=ElasticConfig(lr_policy="linear"))
        try:
            assert getattr(net.conf.base.updater, "_lr_scale", 1.0) == 0.5
        finally:
            net.conf.base.updater._lr_scale = 1.0   # don't leak across tests

    def test_lagging_barrier_restores_agreed_step_not_newest(self,
                                                            tmp_path):
        # a participant AHEAD of the agreement must roll back to the
        # agreed checkpoint (not re-load its own newest) and must not
        # write an ahead-of-agreement coordinated checkpoint
        from deeplearning4j_tpu.parallel.elastic import CoordinationService

        class Lagging(CoordinationService):
            def resume_barrier(self, participant, step, timeout=60.0):
                return step - 2     # someone else is two steps behind

        d = str(tmp_path / "c")
        plan = FaultPlan(device_loss_at_step=5, lose_devices=[4, 5, 6, 7])
        net = mlp()
        w = ParallelWrapper(net)
        w.fit(iterator(), epochs=1,
              checkpoint=CheckpointConfig(d, every_steps=1, keep_last=50),
              elastic=ElasticConfig(coordinator=Lagging()), faults=plan)
        assert net._iteration == NBATCH
        assert w.mesh.size("data") == 4
        mgr = CheckpointManager(CheckpointConfig(d))
        statuses = {s: mgr.validate(p)["status"]
                    for s, p in mgr.checkpoints()}
        assert "elastic-shrink" not in statuses.values()
        # steps 4 and 5 were rolled back and REplayed on the shrunk mesh:
        # the post-shrink periodic saves re-wrote them
        assert {4, 5}.issubset(statuses)

    def test_dispatch_fence_discards_abandoned_commit(self):
        # an abandoned hung dispatch that completes AFTER the shrink
        # bumped the fence must not commit its result or run any
        # bookkeeping (iteration, iterationDone listeners, after hooks) —
        # the recovery that bumped the fence owns the model state (it
        # restores from checkpoint: the dispatch DONATED the old buffers)
        from deeplearning4j_tpu.parallel.elastic import DispatchFence
        from deeplearning4j_tpu.train.resilience import _device_copy
        net = mlp()
        ds = next(iter(iterator()))
        net._fit_one(ds)                      # warm/compile
        saved = (_device_copy(net._params), _device_copy(net._states),
                 _device_copy(net._opt_state))
        fence = DispatchFence()
        net._dispatch_fence = fence
        done = []

        class BumpMidDispatch:
            def onIterationStart(self, model, iteration):
                fence.generation += 1         # "shrink" lands mid-flight

            def iterationDone(self, model, iteration, epoch):
                done.append(iteration)
        net.setListeners(BumpMidDispatch())
        before_iter = net._iteration
        net._fit_one(ds)
        assert net._iteration == before_iter      # no bookkeeping
        assert done == []                         # no iterationDone
        # the recovery path restores state after the void; emulate it and
        # confirm training continues normally once the fence is cleared
        net._params, net._states, net._opt_state = saved
        net._t_dev = None
        net._dispatch_fence = None
        net.setListeners()
        net._fit_one(ds)
        assert net._iteration == before_iter + 1

    def test_bad_lr_policy_rejected_up_front(self, tmp_path):
        with pytest.raises(ValueError, match="lr_policy"):
            ParallelWrapper(mlp()).fit(
                iterator(), checkpoint=CheckpointConfig(str(tmp_path)),
                elastic=ElasticConfig(lr_policy="Linear"))

    def test_restore_specific_step(self, tmp_path):
        d = str(tmp_path / "c")
        net = mlp()
        net.fit(iterator(), checkpoint=CheckpointConfig(d, every_steps=2,
                                                        keep_last=50))
        mgr = CheckpointManager(CheckpointConfig(d))
        assert [s for s, _ in mgr.checkpoints()] == [2, 4, 6, 8, 10]
        target = mlp()
        info = mgr.restore(target, step=4)
        assert info["manifest"]["step"] == 4 and target._iteration == 4
        assert mgr.restore(mlp(), step=5) is None     # absent step

    def test_preemption_composes_with_elastic(self, tmp_path):
        d = str(tmp_path / "c")
        net = mlp()
        ParallelWrapper(net).fit(
            iterator(), epochs=1, checkpoint=CheckpointConfig(d),
            elastic=ElasticConfig(), faults=FaultPlan(preempt_at_step=6))
        assert net._preempted and net._iteration == 6
        _, manifest = CheckpointManager(CheckpointConfig(d)).latest_valid()
        assert manifest["status"] == "preempted"


# ===================================================== data-pipeline rebind
class TestPrefetcherRebindAfterShrink:
    """Satellite: a mesh shrink discards staged megabatches laid out for
    the OLD mesh instead of dispatching them; a new prefetcher with the
    new placement serves the remaining batches."""

    def _placement(self, mesh):
        def place(a, mega):
            ndim = np.ndim(a)
            if not mega:
                return jax.device_put(a, mesh.batch_sharding(ndim))
            return jax.device_put(
                a, mesh.sharding(None, "data", *([None] * (ndim - 2))))
        return place

    def _pulls(self, it):
        # feed the prefetcher a generator, as the elastic loop does — a
        # bare DataSetIterator source would be reset by iter()
        while it.hasNext():
            yield it.next()

    def test_staged_items_discarded_then_rebind(self, devices8):
        it = iterator()
        mesh8 = DeviceMesh.data_parallel()
        pf = DevicePrefetcher(self._pulls(it), steps_per_dispatch=1,
                              prefetch=4, placement=self._placement(mesh8))
        first = next(iter(pf))
        assert len(first.features.sharding.device_set) == 8
        time.sleep(0.2)                 # let the worker stage ahead
        pf.close()                      # shrink: staged items discarded
        consumed_pos = it.cursor()["pos"]
        assert consumed_pos > BATCH     # the worker really pulled ahead
        # rebind: seek back to just after the applied batch, new mesh
        it.seek({"pos": BATCH, "epoch": 0})
        mesh4 = DeviceMesh.create(data=4, devices=devices8[:4])
        with DevicePrefetcher(self._pulls(it), steps_per_dispatch=1,
                              prefetch=2,
                              placement=self._placement(mesh4)) as pf2:
            rest = list(pf2)
        assert len(rest) == NBATCH - 1
        assert all(len(b.features.sharding.device_set) == 4 for b in rest)

    def test_sharded_iterator_cursor_protocol(self):
        from deeplearning4j_tpu.parallel.data import ShardedDataSetIterator
        it = ShardedDataSetIterator(iterator(), process_count=2,
                                    process_index=0)
        it.next()
        c = it.cursor()
        assert c == {"pos": BATCH, "epoch": 0}
        nxt = it.next()
        it2 = ShardedDataSetIterator(iterator(), process_count=2,
                                     process_index=0)
        it2.seek(c)
        np.testing.assert_array_equal(it2.next().features, nxt.features)
        # a batch buffered by hasNext() makes the cursor unusable: None
        it.hasNext()
        assert it.cursor() is None


# ======================================================== parallel inference
class _FlakyOutputModel:
    """model.output raises for the first ``fail`` calls, then delegates."""

    def __init__(self, base, fail=1, sleep=0.0):
        self.base = base
        self._fail = fail
        self._sleep = sleep

    def output(self, x):
        if self._fail > 0:
            self._fail -= 1
            if self._sleep:
                time.sleep(self._sleep)
                return self.base.output(x)
            raise RuntimeError("injected replica failure")
        return self.base.output(x)


class TestParallelInferenceRobustness:
    def _net(self):
        conf = (NeuralNetConfiguration.Builder().seed(1)
                .updater(updaters.Sgd(0.1)).list()
                .layer(DenseLayer(nOut=8, activation="relu"))
                .layer(OutputLayer(nOut=3, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(4))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_flaky_replica_retried(self, devices8):
        net = self._net()
        before = _INFERENCE_REPLICA_FAILURES.value
        pi = ParallelInference(_FlakyOutputModel(net, fail=1),
                               DeviceMesh.data_parallel(), max_retries=2)
        try:
            x = np.random.RandomState(0).randn(4, 4).astype(np.float32)
            with pytest.warns(UserWarning, match="replica failure"):
                out = pi.output(x, timeout=30)
            np.testing.assert_allclose(out, np.asarray(net.output(x)),
                                       rtol=1e-4, atol=1e-5)
            assert _INFERENCE_REPLICA_FAILURES.value == before + 1
        finally:
            pi.shutdown()

    def test_exhausted_retries_structured_error(self, devices8):
        net = self._net()
        pi = ParallelInference(_FlakyOutputModel(net, fail=99),
                               DeviceMesh.data_parallel(), max_retries=1)
        try:
            obs = pi.submit(np.zeros((2, 4), np.float32))
            with pytest.warns(UserWarning, match="replica failure"):
                with pytest.raises(InferenceFailedError,
                                   match="after 2 attempt"):
                    obs.get(timeout=30)
        finally:
            pi.shutdown()

    def test_timed_out_replica_retried(self, devices8):
        net = self._net()
        x = np.random.RandomState(1).randn(2, 4).astype(np.float32)
        net.output(x)   # pre-compile so the timeout only measures the stall
        before = _INFERENCE_REPLICA_FAILURES.value
        pi = ParallelInference(_FlakyOutputModel(net, fail=1, sleep=0.6),
                               DeviceMesh.data_parallel(), max_retries=2,
                               replica_timeout=0.2)
        pi._watchdog._lenient = 0       # compile already done above
        try:
            with pytest.warns(UserWarning, match="replica failure"):
                out = pi.output(x, timeout=30)
            np.testing.assert_allclose(out, np.asarray(net.output(x)),
                                       rtol=1e-4, atol=1e-5)
            assert _INFERENCE_REPLICA_FAILURES.value >= before + 1
        finally:
            pi.shutdown()
            time.sleep(0.5)     # let the abandoned forward finish cleanly

    def test_tensor_parallel_mesh_not_flattened(self, devices8):
        # a TP serving mesh cannot drop devices (each holds a shard):
        # the failure retries on the FULL mesh instead of rebuilding a
        # data-parallel one that would break the model's sharding
        net = self._net()
        plan = FaultPlan(device_loss_at_step=1, lose_devices=[7])
        pi = ParallelInference(_FlakyOutputModel(net, fail=1),
                               DeviceMesh.create(data=4, model=2),
                               max_retries=2, faults=plan)
        try:
            x = np.random.RandomState(3).randn(4, 4).astype(np.float32)
            with pytest.warns(UserWarning,
                              match="cannot shrink a tensor-parallel"):
                out = pi.output(x, timeout=30)
            np.testing.assert_allclose(out, np.asarray(net.output(x)),
                                       rtol=1e-4, atol=1e-5)
            assert pi.mesh.size("model") == 2     # mesh untouched
        finally:
            pi.shutdown()

    def test_dead_devices_dropped_from_serving_mesh(self, devices8):
        net = self._net()
        plan = FaultPlan(device_loss_at_step=1, lose_devices=[4, 5, 6, 7])
        pi = ParallelInference(_FlakyOutputModel(net, fail=1),
                               DeviceMesh.data_parallel(), max_retries=2,
                               faults=plan)
        try:
            x = np.random.RandomState(2).randn(4, 4).astype(np.float32)
            with pytest.warns(UserWarning, match="dropping dead device"):
                out = pi.output(x, timeout=30)
            np.testing.assert_allclose(out, np.asarray(net.output(x)),
                                       rtol=1e-4, atol=1e-5)
            assert pi.mesh.size("data") == 4
            assert {d.id for d in pi.mesh.devices} == {0, 1, 2, 3}
        finally:
            pi.shutdown()


# ===================================================================== chaos
@pytest.mark.chaos
class TestElasticChaosSweep:
    """Seeded elastic sweeps (tier-1 gate: chaos is a fast marker, not a
    slow one): whatever step the seed draws for the device loss — and
    whatever NaN batches ride along — a checkpointed elastic fit must
    shrink, finish all steps, and end with finite params."""

    @pytest.mark.parametrize("policy", [NanPolicy.SKIP_STEP,
                                        NanPolicy.BACKOFF_LR,
                                        NanPolicy.ROLLBACK])
    @pytest.mark.parametrize("seed", range(2))
    def test_device_loss_times_nan_policy(self, seed, policy, tmp_path):
        plan = FaultPlan.seeded(seed, horizon=NBATCH - 1, n_nan=1,
                                n_data_errors=0, device_loss=4,
                                device_pool=range(8))
        d = str(tmp_path / "c")
        net = mlp()
        w = ParallelWrapper(net)
        w.fit(iterator(), epochs=1,
              checkpoint=CheckpointConfig(d, every_steps=2, io_backoff=0.01),
              nan_policy=policy, elastic=ElasticConfig(), faults=plan)
        try:
            if policy is NanPolicy.ROLLBACK:
                # a rollback rewinds the step counter to the restored
                # checkpoint without rewinding the data stream, so the
                # run legitimately ends a few steps short
                assert NBATCH - 3 <= net._iteration <= NBATCH
            else:
                assert net._iteration == NBATCH
            assert np.isfinite(np.asarray(net.params())).all()
            assert w.mesh.size("data") == 4
        finally:
            net.conf.base.updater._lr_scale = 1.0   # BACKOFF_LR hygiene

    @pytest.mark.parametrize("seed", range(2))
    def test_hung_dispatch_sweep(self, seed, tmp_path):
        rng = np.random.RandomState(seed)
        step = int(rng.randint(3, NBATCH))
        before = WATCHDOG_TIMEOUTS.value
        d = str(tmp_path / "c")
        net = mlp()
        ParallelWrapper(net).fit(
            iterator(), epochs=1, checkpoint=CheckpointConfig(d),
            elastic=ElasticConfig(watchdog_deadline=0.1,
                                  watchdog_grace=30.0),
            faults=FaultPlan(hung_dispatch_at=[step], hang_seconds=0.4))
        assert net._iteration == NBATCH
        assert WATCHDOG_TIMEOUTS.value == before + 1
        assert np.isfinite(np.asarray(net.params())).all()
