"""nn-stack tests: configs, layers, MultiLayerNetwork training.

Reference test-strategy parity (SURVEY.md §4): whole-network gradient
checks in fp64, end-to-end small trainings asserting loss decrease /
accuracy, save-load exact-parity round trips.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data import (DataSet, IrisDataSetIterator,
                                     ListDataSetIterator, MnistDataSetIterator,
                                     NormalizerStandardize, AsyncDataSetIterator)
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import (ActivationLayer, BatchNormalization,
                                          Bidirectional, ConvolutionLayer,
                                          DenseLayer, DropoutLayer,
                                          GlobalPoolingLayer, LastTimeStep,
                                          LSTM, OutputLayer, RnnOutputLayer,
                                          SimpleRnn, SubsamplingLayer)
from deeplearning4j_tpu.train import ScoreIterationListener, updaters


def iris_split():
    it = IrisDataSetIterator(150)
    ds = it.next()
    ds.shuffle(seed=0)
    norm = NormalizerStandardize()
    norm.fit(ds)
    norm.transform(ds)
    return ds.splitTestAndTrain(0.8)


def mlp_conf(lr=0.05, **base_kw):
    b = NeuralNetConfiguration.Builder().seed(42).updater(updaters.Adam(lr))
    for k, v in base_kw.items():
        getattr(b, k)(v)
    return (b.list()
            .layer(DenseLayer(nOut=16, activation="relu"))
            .layer(DenseLayer(nOut=16, activation="relu"))
            .layer(OutputLayer(nOut=3, lossFunction="mcxent", activation="softmax"))
            .setInputType(InputType.feedForward(4))
            .build())


class TestMLP:
    def test_iris_trains_to_90pct(self):
        split = iris_split()
        net = MultiLayerNetwork(mlp_conf()).init()
        train_it = ListDataSetIterator(split.getTrain(), 16, shuffle=True)
        net.fit(train_it, epochs=40)
        ev = net.evaluate(ListDataSetIterator(split.getTest(), 30))
        assert ev.accuracy() >= 0.9, ev.stats()

    def test_listener_sees_scores(self):
        split = iris_split()
        net = MultiLayerNetwork(mlp_conf()).init()
        lst = ScoreIterationListener(1, out=lambda m: None)
        net.setListeners(lst)
        net.fit(ListDataSetIterator(split.getTrain(), 32), epochs=2)
        assert len(lst.history) > 0
        assert lst.history[-1] < lst.history[0] * 2  # sane values

    def test_flat_params_roundtrip(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        flat = net.params()
        assert flat.shape[0] == net.numParams() == 4 * 16 + 16 + 16 * 16 + 16 + 16 * 3 + 3
        net2 = MultiLayerNetwork(mlp_conf()).init(seed=999)
        net2.setParams(flat)
        np.testing.assert_allclose(net2.params(), flat)
        x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(net.output(x), net2.output(x), rtol=1e-6)

    def test_summary(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        s = net.summary()
        assert "Total params" in s and "DenseLayer" in s

    def test_gradient_check_whole_net(self):
        """fp64 finite differences through the whole network
        (ref: org.deeplearning4j.gradientcheck.GradientCheckTests)."""
        with jax.experimental.enable_x64():
            conf = (NeuralNetConfiguration.Builder().seed(7)
                    .updater(updaters.Sgd(0.1)).dataType("float64")
                    .list()
                    .layer(DenseLayer(nOut=5, activation="tanh"))
                    .layer(OutputLayer(nOut=2, lossFunction="mcxent",
                                       activation="softmax"))
                    .setInputType(InputType.feedForward(3))
                    .build())
            net = MultiLayerNetwork(conf).init()
            net._params = jax.tree_util.tree_map(
                lambda p: p.astype(jnp.float64), net._params)
            rng = np.random.RandomState(3)
            x = jnp.asarray(rng.randn(4, 3))
            y = jnp.asarray(np.eye(2)[rng.randint(0, 2, 4)])

            def loss_of(params):
                l, _ = net._loss_and_reg(params, net._states, x, y, False,
                                         jax.random.PRNGKey(0), None, None)
                return l

            grads = jax.grad(loss_of)(net._params)
            eps = 1e-6
            for li in (0, 1):
                for name in net._params[li]:
                    arr = np.asarray(net._params[li][name], np.float64)
                    g = np.asarray(grads[li][name]).ravel()
                    for idx in range(0, arr.size, max(1, arr.size // 4)):
                        pert = arr.copy().ravel()
                        pert[idx] += eps
                        pp = [dict(p) for p in net._params]
                        pp[li][name] = jnp.asarray(pert.reshape(arr.shape))
                        fp = float(loss_of(pp))
                        pert[idx] -= 2 * eps
                        pp[li][name] = jnp.asarray(pert.reshape(arr.shape))
                        fm = float(loss_of(pp))
                        fd = (fp - fm) / (2 * eps)
                        np.testing.assert_allclose(g[idx], fd, rtol=1e-4, atol=1e-8)


class TestLeNet:
    def lenet_conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(123)
                .updater(updaters.Adam(1e-3))
                .weightInit("xavier")
                .list()
                .layer(ConvolutionLayer(kernelSize=(5, 5), stride=(1, 1),
                                        nOut=8, activation="identity"))
                .layer(SubsamplingLayer(poolingType="max", kernelSize=(2, 2),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(kernelSize=(5, 5), stride=(1, 1),
                                        nOut=16, activation="identity"))
                .layer(SubsamplingLayer(poolingType="max", kernelSize=(2, 2),
                                        stride=(2, 2)))
                .layer(DenseLayer(nOut=32, activation="relu"))
                .layer(OutputLayer(nOut=10, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.convolutionalFlat(28, 28, 1))
                .build())

    def test_shape_inference(self):
        conf = self.lenet_conf()
        # conv(5x5) 28->24, pool 24->12, conv 12->8, pool 8->4 → dense in 16*4*4
        assert conf.layers[4].nIn == 16 * 4 * 4
        net = MultiLayerNetwork(conf).init()
        out = net.output(np.zeros((2, 784), np.float32))
        assert out.shape == (2, 10)

    def test_lenet_learns_synthetic_mnist(self):
        train_it = MnistDataSetIterator(64, True, num_examples=512)
        test_it = MnistDataSetIterator(128, False, num_examples=256)
        net = MultiLayerNetwork(self.lenet_conf()).init()
        net.fit(train_it, epochs=6)
        ev = net.evaluate(test_it)
        assert ev.accuracy() > 0.85, ev.stats()

    def test_lenet_pinned_99pct_bar(self):
        """The BASELINE 'LeNet >=99%' correctness row, pinned on the
        deterministic synthetic digit task (no MNIST IDX files in this
        image — VERDICT r4 weak #3): fixed seeds, fixed data, fixed
        config, measured 1.00 at pin time. A regression anywhere in the
        conv/pool/dense/optimizer path shows up here as <0.99."""
        train_it = MnistDataSetIterator(64, True, num_examples=2048)
        test_it = MnistDataSetIterator(256, False, num_examples=512)
        net = MultiLayerNetwork(self.lenet_conf()).init()
        net.fit(train_it, epochs=8)
        ev = net.evaluate(test_it)
        assert ev.accuracy() >= 0.99, ev.stats()


class TestRecurrentNet:
    def test_lstm_sequence_classification(self):
        """Sequences whose mean sign determines the class; LastTimeStep +
        dense head."""
        rng = np.random.RandomState(0)
        N, C, T = 128, 3, 10
        y = rng.randint(0, 2, N)
        x = rng.randn(N, C, T).astype(np.float32) * 0.5
        x += (y * 2 - 1)[:, None, None] * 0.6
        labels = np.eye(2, dtype=np.float32)[y]
        conf = (NeuralNetConfiguration.Builder().seed(1)
                .updater(updaters.Adam(0.01))
                .list()
                .layer(LastTimeStep(LSTM(nOut=8)))
                .layer(OutputLayer(nOut=2, lossFunction="mcxent", activation="softmax"))
                .setInputType(InputType.recurrent(3, T))
                .build())
        net = MultiLayerNetwork(conf).init()
        it = ListDataSetIterator(DataSet(x, labels), 32, shuffle=True)
        net.fit(it, epochs=8)
        ev = net.evaluate(ListDataSetIterator(DataSet(x, labels), 64))
        assert ev.accuracy() >= 0.9, ev.stats()

    def test_rnn_output_layer_with_masks(self):
        """Per-timestep outputs + label masks (ref: masking is first-class)."""
        rng = np.random.RandomState(1)
        N, C, T = 64, 2, 8
        x = rng.randn(N, C, T).astype(np.float32)
        y = (x.sum(axis=1, keepdims=True) > 0).astype(np.float32)
        labels = np.concatenate([y, 1 - y], axis=1)  # [N, 2, T]
        lengths = rng.randint(3, T + 1, N)
        mask = (np.arange(T)[None, :] < lengths[:, None]).astype(np.float32)
        conf = (NeuralNetConfiguration.Builder().seed(2)
                .updater(updaters.Adam(0.02))
                .list()
                .layer(SimpleRnn(nOut=8))
                .layer(RnnOutputLayer(nOut=2, lossFunction="mcxent",
                                      activation="softmax"))
                .setInputType(InputType.recurrent(2, T))
                .build())
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(x, labels, features_mask=mask, labels_mask=mask)
        first = None
        for _ in range(30):
            net.fit(ds)
            first = first if first is not None else net.score()
        assert net.score() < first

    def test_bidirectional_shapes(self):
        conf = (NeuralNetConfiguration.Builder().seed(3)
                .updater(updaters.Sgd(0.1))
                .list()
                .layer(Bidirectional(LSTM(nOut=4), mode="concat"))
                .layer(GlobalPoolingLayer("avg"))
                .layer(OutputLayer(nOut=2, lossFunction="mcxent", activation="softmax"))
                .setInputType(InputType.recurrent(3, 5))
                .build())
        net = MultiLayerNetwork(conf).init()
        out = net.output(np.zeros((2, 3, 5), np.float32))
        assert out.shape == (2, 2)


class TestBatchNormDropout:
    def test_batchnorm_updates_running_stats(self):
        conf = (NeuralNetConfiguration.Builder().seed(4)
                .updater(updaters.Sgd(0.01))
                .list()
                .layer(DenseLayer(nOut=8, activation="identity"))
                .layer(BatchNormalization())
                .layer(ActivationLayer("relu"))
                .layer(OutputLayer(nOut=2, lossFunction="mcxent", activation="softmax"))
                .setInputType(InputType.feedForward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        before = np.asarray(net._states[1]["mean"]).copy()
        rng = np.random.RandomState(0)
        ds = DataSet(rng.randn(32, 4).astype(np.float32) + 3.0,
                     np.eye(2, dtype=np.float32)[rng.randint(0, 2, 32)])
        net.fit(ds)
        after = np.asarray(net._states[1]["mean"])
        assert not np.allclose(before, after)
        # inference uses running stats deterministically
        out1 = net.output(ds.features)
        out2 = net.output(ds.features)
        np.testing.assert_allclose(out1, out2)

    def test_dropout_only_in_training(self):
        conf = (NeuralNetConfiguration.Builder().seed(5)
                .updater(updaters.Sgd(0.01))
                .list()
                .layer(DenseLayer(nOut=32, activation="relu"))
                .layer(DropoutLayer(dropOut=0.5))
                .layer(OutputLayer(nOut=2, lossFunction="mcxent", activation="softmax"))
                .setInputType(InputType.feedForward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        np.testing.assert_allclose(net.output(x), net.output(x))


class TestSerialization:
    def test_save_restore_exact(self, tmp_path):
        split = iris_split()
        net = MultiLayerNetwork(mlp_conf()).init()
        it = ListDataSetIterator(split.getTrain(), 32)
        net.fit(it, epochs=3)
        path = str(tmp_path / "model.zip")
        net.save(path)
        net2 = MultiLayerNetwork.load(path)
        x = split.getTest().features
        np.testing.assert_allclose(np.asarray(net.output(x)),
                                   np.asarray(net2.output(x)), rtol=1e-6)
        # exact training resume: same next-step score
        net.fit(split.getTrain())
        net2.fit(split.getTrain())
        np.testing.assert_allclose(net.score(), net2.score(), rtol=1e-5)

    def test_config_json_roundtrip(self):
        from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
        conf = mlp_conf()
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        assert len(conf2.layers) == 3
        assert conf2.layers[0].nIn == 4
        net = MultiLayerNetwork(conf2).init()
        assert net.output(np.zeros((1, 4), np.float32)).shape == (1, 3)


class TestIterators:
    def test_async_iterator_matches(self):
        base = IrisDataSetIterator(32)
        async_it = AsyncDataSetIterator(IrisDataSetIterator(32))
        n_base = sum(ds.numExamples() for ds in base)
        n_async = sum(ds.numExamples() for ds in async_it)
        assert n_base == n_async == 150
        # reusable after reset
        assert sum(ds.numExamples() for ds in async_it) == 150

    def test_normalizer_standardize(self):
        rng = np.random.RandomState(0)
        ds = DataSet(rng.randn(100, 5).astype(np.float32) * 7 + 3,
                     np.zeros((100, 1), np.float32))
        norm = NormalizerStandardize()
        norm.fit(ds)
        norm.transform(ds)
        assert abs(ds.features.mean()) < 0.1
        assert abs(ds.features.std() - 1.0) < 0.1
