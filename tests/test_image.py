"""DataVec image pipeline tests: loaders, transforms, ImageRecordReader,
ObjectDetectionRecordReader feeding YOLO training from on-disk images.

Reference parity: ``datavec-data-image`` test suite shape (SURVEY.md §2.2
"DataVec image/audio"): reader tests over small generated file trees,
transform unit tests, and the objdetect reader emitting
``Yolo2OutputLayer``'s label layout.
"""

import os

import numpy as np
import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402

from deeplearning4j_tpu.data.image import (BrightnessTransform,
                                           ColorConversionTransform,
                                           CropImageTransform,
                                           FlipImageTransform,
                                           ImageRecordReader,
                                           ImageRecordReaderDataSetIterator,
                                           NativeImageLoader,
                                           ObjectDetectionDataSetIterator,
                                           ObjectDetectionRecordReader,
                                           PipelineImageTransform,
                                           ResizeImageTransform,
                                           RotateImageTransform,
                                           ScaleImageTransform)


def _write_image(path, hw=(24, 24), color=(255, 0, 0)):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    Image.new("RGB", (hw[1], hw[0]), color).save(path)


def _make_class_tree(root, classes=("cat", "dog"), per_class=4):
    for ci, cls in enumerate(classes):
        for i in range(per_class):
            _write_image(os.path.join(root, cls, f"{i}.png"),
                         color=(50 * (ci + 1), 10 * i, 0))


class TestLoaderAndTransforms:
    def test_loader_resizes_to_chw(self, tmp_path):
        p = str(tmp_path / "a.png")
        _write_image(p, hw=(10, 20), color=(1, 2, 3))
        img = NativeImageLoader(8, 8, 3).asMatrix(p)
        assert img.shape == (3, 8, 8)
        np.testing.assert_allclose(img[0], 1, atol=1.0)

    def test_grayscale_channel(self, tmp_path):
        p = str(tmp_path / "a.png")
        _write_image(p)
        img = NativeImageLoader(8, 8, 1).asMatrix(p)
        assert img.shape == (1, 8, 8)

    def test_transforms_shapes_and_values(self):
        rng = np.random.RandomState(0)
        img = rng.rand(3, 16, 16).astype(np.float32) * 255
        assert ResizeImageTransform(8, 12).transform(img, rng).shape == (3, 8, 12)
        flipped = FlipImageTransform(1).transform(img, rng)
        np.testing.assert_array_equal(flipped, img[:, :, ::-1])
        cropped = CropImageTransform(4).transform(img, rng)
        assert cropped.shape[1] <= 16 and cropped.shape[2] <= 16
        np.testing.assert_allclose(
            ScaleImageTransform(0.5).transform(img, rng), img * 0.5)
        bright = BrightnessTransform(10.0).transform(img, rng)
        assert bright.max() <= 255.0
        gray = ColorConversionTransform().transform(img, rng)
        np.testing.assert_allclose(gray[0], gray[1])
        rot = RotateImageTransform(90).transform(img, rng)
        assert rot.shape == img.shape

    def test_pipeline_applies_in_order(self):
        rng = np.random.RandomState(0)
        img = np.ones((1, 8, 8), np.float32)
        pipe = PipelineImageTransform([
            (ScaleImageTransform(2.0), 1.0),
            (ScaleImageTransform(3.0), 1.0),
        ])
        out = pipe.transform(img, rng)
        np.testing.assert_allclose(out, img * 6.0)


class TestImageRecordReader:
    def test_reader_labels_from_parent_dirs(self, tmp_path):
        _make_class_tree(str(tmp_path))
        rr = ImageRecordReader(12, 12, 3).initialize(str(tmp_path))
        assert rr.labels == ["cat", "dog"]
        assert rr.numLabels() == 2
        recs = list(rr)
        assert len(recs) == 8
        img, lab = recs[0]
        assert img.value.shape == (3, 12, 12)
        assert lab.value in (0, 1)

    def test_iterator_batches_nchw(self, tmp_path):
        _make_class_tree(str(tmp_path))
        rr = ImageRecordReader(12, 12, 3).initialize(str(tmp_path))
        it = ImageRecordReaderDataSetIterator(rr, batch_size=3)
        ds = it.next()
        assert ds.features.shape == (3, 3, 12, 12)
        assert ds.labels.shape == (3, 2)
        n = ds.features.shape[0]
        while it.hasNext():
            n += it.next().features.shape[0]
        assert n == 8

    def test_lenet_trains_from_disk(self, tmp_path):
        from deeplearning4j_tpu.models import zoo
        _make_class_tree(str(tmp_path), classes=("a", "b", "c"), per_class=3)
        rr = ImageRecordReader(16, 16, 1).initialize(str(tmp_path))
        it = ImageRecordReaderDataSetIterator(rr, batch_size=9)
        net = zoo.LeNet(num_classes=3, input_shape=(1, 16, 16)).init()
        net.fit(it)
        assert np.isfinite(net.score())


class TestObjectDetection:
    def _provider(self, boxes_by_file):
        return lambda path: boxes_by_file.get(os.path.basename(path), [])

    def test_label_tensor_layout(self, tmp_path):
        p = str(tmp_path / "imgs" / "x.png")
        _write_image(p, hw=(64, 64))
        provider = self._provider(
            {"x.png": [(8, 16, 24, 48, "dog")]})   # pixel coords on 64x64
        rr = ObjectDetectionRecordReader(
            32, 32, 3, grid_h=4, grid_w=4, label_provider=provider,
            classes=["cat", "dog"]).initialize(str(tmp_path / "imgs"))
        img_w, lab_w = rr.next()
        lab = lab_w.value
        assert img_w.value.shape == (3, 32, 32)
        assert lab.shape == (4 + 2, 4, 4)
        # box center in grid units: x=(0.5+1.5)/2=1, y=(1+3)/2=2
        assert lab[4 + 1, 2, 1] == 1.0          # class 'dog' one-hot
        np.testing.assert_allclose(lab[0:4, 2, 1], [0.5, 1.0, 1.5, 3.0])
        assert lab[:, 0, 0].sum() == 0          # other cells empty

    def test_yolo_trains_from_disk(self, tmp_path):
        """VERDICT r2 'Done' criterion: YOLO trains a step from on-disk
        images through the ObjectDetection pipeline."""
        from deeplearning4j_tpu.nn.config import (InputType,
                                                  NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import ConvolutionLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.objdetect import Yolo2OutputLayer
        from deeplearning4j_tpu.train import updaters

        img_dir = str(tmp_path / "voc")
        boxes = {}
        for i in range(6):
            _write_image(os.path.join(img_dir, f"im{i}.png"), hw=(32, 32),
                         color=(0, 100 + 20 * i, 0))
            boxes[f"im{i}.png"] = [(4, 4, 20, 24, "obj")]
        grid, n_classes, n_boxes = 4, 1, 2
        rr = ObjectDetectionRecordReader(
            grid, grid, 3, grid_h=grid, grid_w=grid,
            label_provider=self._provider(boxes),
            classes=["obj"]).initialize(img_dir)
        it = ObjectDetectionDataSetIterator(rr, batch_size=6)
        # raw [0, 255] pixels through exp(wh) overflow Adam's fp32 second
        # moment (g^2 ~ 1e68 -> inf -> zero updates); the reference
        # pipeline scales pixels first, same here
        from deeplearning4j_tpu.data.dataset import ImagePreProcessingScaler
        it.setPreProcessor(ImagePreProcessingScaler())
        anchors = np.asarray([[1.0, 1.0], [2.5, 2.5]], np.float32)
        conf = (NeuralNetConfiguration.Builder().seed(7)
                .updater(updaters.Adam(1e-3)).list()
                .layer(ConvolutionLayer(kernelSize=(3, 3), padding=(1, 1),
                                        nOut=8, activation="relu"))
                .layer(ConvolutionLayer(kernelSize=(1, 1),
                                        nOut=n_boxes * (5 + n_classes),
                                        activation="identity"))
                .layer(Yolo2OutputLayer(boundingBoxPriors=anchors))
                .setInputType(InputType.convolutional(grid, grid, 3))
                .build())
        net = MultiLayerNetwork(conf).init()
        first = None
        for _ in range(10):
            it.reset()
            net.fit(it)
            if first is None:
                first = net.score()
        assert np.isfinite(net.score())
        assert net.score() < first

    def test_hflip_transform_maps_boxes(self, tmp_path):
        p = str(tmp_path / "i" / "x.png")
        _write_image(p, hw=(32, 32))
        provider = self._provider({"x.png": [(4, 8, 12, 16, "c")]})
        rr = ObjectDetectionRecordReader(
            32, 32, 3, grid_h=4, grid_w=4, label_provider=provider,
            classes=["c"], transform=FlipImageTransform(1)
        ).initialize(str(tmp_path / "i"))
        _, lab_w = rr.next()
        lab = lab_w.value
        # flipped box: x1 = 32-12=20 -> grid 2.5, x2 = 32-4=28 -> grid 3.5
        cy, cx = 1, 3   # center (24,12) px -> grid (3, 1.5) -> cell x=3,y=1
        np.testing.assert_allclose(lab[0:4, cy, cx], [2.5, 1.0, 3.5, 2.0])


class TestCifar10:
    def test_iterator_shapes(self):
        from deeplearning4j_tpu.data.iterators import Cifar10DataSetIterator
        it = Cifar10DataSetIterator(16, num_examples=64)
        ds = it.next()
        assert ds.features.shape == (16, 3, 32, 32)
        assert ds.labels.shape == (16, 10)
        assert 0.0 <= np.asarray(ds.features).min() \
            and np.asarray(ds.features).max() <= 1.0


class TestTransformProcessNewOps:
    def test_numeric_string_time_ops(self):
        from deeplearning4j_tpu.data.records import (ColumnType, Schema,
                                                     TransformProcess)
        schema = (Schema.Builder()
                  .addColumnDouble("v")
                  .addColumnString("s")
                  .addColumnString("ts")
                  .build())
        tp = (TransformProcess.Builder(schema)
              .doubleMathFunction("v", "Sqrt")
              .clipValues("v", 0.0, 2.0)
              .addConstantColumn("k", ColumnType.DOUBLE, 10.0)
              .doubleColumnsMathOp("vk", "Multiply", "v", "k")
              .changeCase("s", "UPPER")
              .appendStringColumnTransform("s", "!")
              .stringToTimeTransform("ts", "%Y-%m-%d %H:%M")
              .deriveColumnsFromTime("ts", "hourOfDay", "dayOfWeek")
              .build())
        rows = tp.execute([[9.0, "abc", "2026-01-05 13:30"],
                           [16.0, "x y", "2026-01-06 07:00"]])
        names = tp.getFinalSchema().getColumnNames()
        r = dict(zip(names, rows[0]))
        assert r["v"] == 2.0            # sqrt(9)=3 clipped to 2
        assert r["vk"] == 20.0
        assert r["s"] == "ABC!"
        assert r["ts[hourOfDay]"] == 13
        assert r["ts[dayOfWeek]"] == 1  # 2026-01-05 is a Monday
        r2 = dict(zip(names, rows[1]))
        assert r2["ts[dayOfWeek]"] == 2

    def test_column_management_ops(self):
        from deeplearning4j_tpu.data.records import Schema, TransformProcess
        schema = (Schema.Builder()
                  .addColumnDouble("a").addColumnDouble("b").build())
        tp = (TransformProcess.Builder(schema)
              .duplicateColumns(["a"], ["a2"])
              .reorderColumns("b", "a")
              .convertToInteger("b")
              .firstDigitTransform("a", "fd")
              .build())
        rows = tp.execute([[123.0, 4.5]])
        names = tp.getFinalSchema().getColumnNames()
        assert names == ["b", "a", "a2", "fd"]
        assert rows[0] == [4, 123.0, 123.0, 1]