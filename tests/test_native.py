"""Native C++ PJRT runtime tests (SURVEY.md §2.1 L0, §7 item 1).

The library builds from source in-test (g++ + the PJRT C API header — both
baked into the image). Execution tests need a PJRT plugin: the axon TPU
tunnel when available, else they skip (there is no CPU PJRT C-API plugin
in this image). jax is used ONLY as a StableHLO producer, pinned to CPU
by tests/conftest.py, so the native client is the sole owner of the TPU
session.
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

from deeplearning4j_tpu.native import runtime as rt_mod
from deeplearning4j_tpu.native import (NativeRuntime, NativeRuntimeError,
                                       build_native_lib)


def test_native_lib_builds():
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    path = build_native_lib()
    assert os.path.exists(path)
    # symbol table sanity: the flat C ABI is present
    out = subprocess.run(["nm", "-D", path], capture_output=True, text=True)
    for sym in ("dl4j_client_create", "dl4j_compile", "dl4j_execute",
                "dl4j_free_outputs", "dl4j_client_cache_stats"):
        assert sym in out.stdout


@pytest.fixture(scope="module")
def native_rt():
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    if not os.path.exists(rt_mod.DEFAULT_PLUGIN):
        pytest.skip(f"no PJRT plugin at {rt_mod.DEFAULT_PLUGIN}")
    try:
        rt = NativeRuntime.create()
    except NativeRuntimeError as e:   # plugin present but chip unclaimable
        pytest.skip(f"PJRT client unavailable: {e}")
    yield rt
    rt.close()


class TestNativeRuntime:
    def test_client_metadata(self, native_rt):
        assert native_rt.device_count >= 1
        assert native_rt.platform_name
        major, minor = native_rt.api_version
        assert (major, minor) >= (0, 40)

    def test_compile_and_execute_matmul(self, native_rt):
        import jax
        import jax.numpy as jnp

        def f(a, b):
            return a @ b + 1.0, jnp.tanh(a).sum()
        mlir = jax.jit(f).lower(jnp.zeros((4, 5), jnp.float32),
                                jnp.zeros((5, 3), jnp.float32)).as_text()
        exe = native_rt.compile(mlir)
        assert exe.num_outputs == 2
        rng = np.random.RandomState(0)
        a = rng.randn(4, 5).astype(np.float32)
        b = rng.randn(5, 3).astype(np.float32)
        outs = exe(a, b)
        np.testing.assert_allclose(outs[0], a @ b + 1.0, rtol=2e-2, atol=1e-2)
        np.testing.assert_allclose(outs[1], np.tanh(a).sum(), rtol=2e-2)

    def test_compile_cache_hits(self, native_rt):
        import jax
        import jax.numpy as jnp
        mlir = jax.jit(lambda x: x * 2.0).lower(
            jnp.zeros((3,), jnp.float32)).as_text()
        e1 = native_rt.compile(mlir)
        e2 = native_rt.compile(mlir)
        assert not e1.cache_hit and e2.cache_hit
        stats = native_rt.cache_stats()
        assert stats["hits"] >= 1 and stats["size"] >= 1
        out = e2(np.asarray([1.0, 2.0, 3.0], np.float32))
        np.testing.assert_allclose(out[0], [2.0, 4.0, 6.0], rtol=1e-3)

    def test_int_dtypes_roundtrip(self, native_rt):
        import jax
        mlir = jax.jit(lambda x: x + 1).lower(
            np.zeros((4,), np.int32)).as_text()
        exe = native_rt.compile(mlir)
        out = exe(np.asarray([1, 2, 3, 4], np.int32))
        np.testing.assert_array_equal(out[0], [2, 3, 4, 5])
        assert out[0].dtype == np.int32

    def test_compile_error_reported(self, native_rt):
        with pytest.raises(NativeRuntimeError, match="compile failed"):
            native_rt.compile("this is not mlir")


class TestNativeExecBackend:
    """backend="native" (VERDICT r4 #6): a SameDiff model's inference runs
    THROUGH the C++ runtime (trace -> StableHLO -> native client) and
    matches the jax path."""

    def test_samediff_mlp_through_native_client(self, native_rt):
        import jax.numpy as jnp
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        rng = np.random.RandomState(0)
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 6), dtype=np.float32)
        w1 = sd.var("w1", rng.randn(6, 8).astype(np.float32))
        b1 = sd.var("b1", np.zeros(8, np.float32))
        w2 = sd.var("w2", rng.randn(8, 3).astype(np.float32))
        h = sd.nn.relu(x.mmul(w1).add(b1))
        out = sd.nn.softmax(h.mmul(w2), name="probs")

        feeds = {"x": rng.randn(4, 6).astype(np.float32)}
        want = np.asarray(sd.output(feeds, ["probs"])["probs"])

        sd.setExecBackend("native")
        got = np.asarray(sd.output(feeds, ["probs"])["probs"])
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=1e-3)

        # compiled-program cache: second call hits the native cache
        got2 = np.asarray(sd.output(feeds, ["probs"])["probs"])
        np.testing.assert_allclose(got2, got, rtol=1e-6)
        sd.setExecBackend("jax")

    def test_imported_zoo_model_native_parity(self, native_rt):
        """A LeNet-sized conv net through the native client."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        rng = np.random.RandomState(1)
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(2, 1, 12, 12), dtype=np.float32)
        w = sd.var("w", (rng.randn(4, 1, 3, 3) * 0.3).astype(np.float32))
        c = sd.cnn.conv2d(x, w, stride=(1, 1), pad=(0, 0))
        r = sd.nn.relu(c)
        p = sd.cnn.maxPooling2d(r, kernel=(2, 2), stride=(2, 2))
        out = sd.math.reduce_mean(p, name="m")
        feeds = {"x": rng.randn(2, 1, 12, 12).astype(np.float32)}
        want = np.asarray(sd.output(feeds, ["m"])["m"])
        sd.setExecBackend("native")
        got = np.asarray(sd.output(feeds, ["m"])["m"])
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=1e-3)
