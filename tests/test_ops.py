"""Op-layer tests.

Reference test-strategy parity (SURVEY.md §4): golden-value conformance —
conv/pool/rnn ops are checked against torch (CPU) goldens the way the
reference pins op semantics to TF via TFGraphTestAllSameDiff; plus
finite-difference gradient checks as the universal backstop.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick
import torch
import torch.nn.functional as F

from deeplearning4j_tpu.ops import convolution as conv
from deeplearning4j_tpu.ops import losses, normalization, recurrent, registry
from deeplearning4j_tpu.ops import attention as attn


def t2j(t):
    return jnp.asarray(t.detach().numpy())


class TestConvGolden:
    def test_conv2d_vs_torch(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        w = rng.randn(4, 3, 3, 3).astype(np.float32)
        b = rng.randn(4).astype(np.float32)
        want = F.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                        stride=2, padding=1).numpy()
        got = conv.conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
                          stride=2, pad=1)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_conv2d_dilated_vs_torch(self):
        rng = np.random.RandomState(1)
        x = rng.randn(1, 2, 10, 10).astype(np.float32)
        w = rng.randn(3, 2, 3, 3).astype(np.float32)
        want = F.conv2d(torch.tensor(x), torch.tensor(w), dilation=2).numpy()
        got = conv.conv2d(jnp.asarray(x), jnp.asarray(w), dilation=2)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_conv2d_groups_vs_torch(self):
        rng = np.random.RandomState(2)
        x = rng.randn(2, 4, 6, 6).astype(np.float32)
        w = rng.randn(8, 2, 3, 3).astype(np.float32)
        want = F.conv2d(torch.tensor(x), torch.tensor(w), groups=2).numpy()
        got = conv.conv2d(jnp.asarray(x), jnp.asarray(w), groups=2)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_depthwise_vs_torch(self):
        rng = np.random.RandomState(3)
        x = rng.randn(2, 3, 7, 7).astype(np.float32)
        # torch depthwise: weight [3*2, 1, k, k] groups=3; ours [mult=2, 3, k, k]
        w_ours = rng.randn(2, 3, 3, 3).astype(np.float32)
        w_torch = w_ours.transpose(1, 0, 2, 3).reshape(6, 1, 3, 3)
        want = F.conv2d(torch.tensor(x), torch.tensor(w_torch), groups=3).numpy()
        got = conv.depthwise_conv2d(jnp.asarray(x), jnp.asarray(w_ours))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_deconv2d_vs_torch(self):
        rng = np.random.RandomState(4)
        x = rng.randn(1, 3, 5, 5).astype(np.float32)
        w_ours = rng.randn(4, 3, 3, 3).astype(np.float32)  # [outC,inC,kH,kW]
        # torch convtranspose weight layout: [inC, outC, kH, kW]
        w_torch = w_ours.transpose(1, 0, 2, 3)
        want = F.conv_transpose2d(torch.tensor(x), torch.tensor(w_torch), stride=2).numpy()
        got = conv.deconv2d(jnp.asarray(x), jnp.asarray(w_ours), stride=2)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_maxpool_vs_torch(self):
        rng = np.random.RandomState(5)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        want = F.max_pool2d(torch.tensor(x), 2, 2).numpy()
        got = conv.maxpool2d(jnp.asarray(x), kernel=2, stride=2)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_avgpool_vs_torch(self):
        rng = np.random.RandomState(6)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        want = F.avg_pool2d(torch.tensor(x), 3, 2).numpy()
        got = conv.avgpool2d(jnp.asarray(x), kernel=3, stride=2)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_conv1d_causal_keeps_length(self):
        x = jnp.ones((2, 4, 10))
        w = jnp.ones((8, 4, 3))
        out = conv.conv1d(x, w, mode="causal")
        assert out.shape == (2, 8, 10)

    def test_same_padding_shape(self):
        x = jnp.ones((1, 3, 9, 9))
        w = jnp.ones((5, 3, 3, 3))
        out = conv.conv2d(x, w, stride=2, mode="same")
        assert out.shape == (1, 5, 5, 5)

    def test_space_depth_roundtrip(self):
        x = jnp.arange(2 * 4 * 4 * 4.0).reshape(2, 4, 4, 4)
        y = conv.space_to_depth(x, 2)
        z = conv.depth_to_space(y, 2)
        np.testing.assert_allclose(z, x)

    def test_upsampling(self):
        x = jnp.arange(4.0).reshape(1, 1, 2, 2)
        y = conv.upsampling2d(x, 2)
        assert y.shape == (1, 1, 4, 4)
        np.testing.assert_allclose(y[0, 0, :2, :2], jnp.full((2, 2), x[0, 0, 0, 0]))


class TestRecurrentGolden:
    def test_lstm_vs_torch(self):
        rng = np.random.RandomState(7)
        T, N, C, H = 5, 3, 4, 6
        x = rng.randn(T, N, C).astype(np.float32)
        m = torch.nn.LSTM(C, H)
        # torch gate order: i, f, g, o — same as ours
        w_ih = m.weight_ih_l0.detach().numpy().T  # [C, 4H]
        w_hh = m.weight_hh_l0.detach().numpy().T
        b = (m.bias_ih_l0 + m.bias_hh_l0).detach().numpy()
        want, (hT, cT) = m(torch.tensor(x))
        outs, (h, c) = recurrent.lstm(jnp.asarray(x), jnp.asarray(w_ih),
                                      jnp.asarray(w_hh), jnp.asarray(b))
        np.testing.assert_allclose(outs, want.detach().numpy(), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(h, hT[0].detach().numpy(), rtol=1e-4, atol=1e-5)

    def test_gru_vs_torch(self):
        rng = np.random.RandomState(8)
        T, N, C, H = 4, 2, 3, 5
        x = rng.randn(T, N, C).astype(np.float32)
        m = torch.nn.GRU(C, H)
        w_ih = m.weight_ih_l0.detach().numpy().T
        w_hh = m.weight_hh_l0.detach().numpy().T
        b_ih = m.bias_ih_l0.detach().numpy()
        b_hh = m.bias_hh_l0.detach().numpy()
        want, hT = m(torch.tensor(x))
        outs, h = recurrent.gru(jnp.asarray(x), jnp.asarray(w_ih),
                                jnp.asarray(w_hh), jnp.asarray(b_ih), jnp.asarray(b_hh))
        np.testing.assert_allclose(outs, want.detach().numpy(), rtol=1e-4, atol=1e-5)

    def test_lstm_mask_freezes_state(self):
        T, N, C, H = 6, 2, 3, 4
        rng = np.random.RandomState(9)
        x = jnp.asarray(rng.randn(T, N, C).astype(np.float32))
        w_ih = jnp.asarray(rng.randn(C, 4 * H).astype(np.float32) * 0.1)
        w_hh = jnp.asarray(rng.randn(H, 4 * H).astype(np.float32) * 0.1)
        b = jnp.zeros((4 * H,), jnp.float32)
        mask = jnp.asarray(np.array([[1, 1], [1, 1], [1, 0], [1, 0], [1, 0], [1, 0]], np.float32))
        outs, (h, c) = recurrent.lstm(x, w_ih, w_hh, b, mask_tn=mask)
        # example 1 masked from t=2: outputs zero, state frozen at t=1
        np.testing.assert_allclose(outs[2:, 1], np.zeros((4, H)), atol=1e-7)
        outs_short, (h_s, _) = recurrent.lstm(x[:2, 1:2], w_ih, w_hh, b)
        np.testing.assert_allclose(h[1], h_s[0], rtol=1e-5, atol=1e-6)


class TestNorm:
    def test_batchnorm_vs_torch(self):
        rng = np.random.RandomState(10)
        x = rng.randn(4, 3, 5, 5).astype(np.float32)
        g = rng.rand(3).astype(np.float32) + 0.5
        b = rng.randn(3).astype(np.float32)
        mean = rng.randn(3).astype(np.float32)
        var = rng.rand(3).astype(np.float32) + 0.5
        want = F.batch_norm(torch.tensor(x), torch.tensor(mean), torch.tensor(var),
                            torch.tensor(g), torch.tensor(b), eps=1e-5).numpy()
        got = normalization.batch_norm(jnp.asarray(x), jnp.asarray(g), jnp.asarray(b),
                                       jnp.asarray(mean), jnp.asarray(var))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_layernorm_vs_torch(self):
        rng = np.random.RandomState(11)
        x = rng.randn(4, 7).astype(np.float32)
        g = rng.rand(7).astype(np.float32)
        b = rng.randn(7).astype(np.float32)
        want = F.layer_norm(torch.tensor(x), (7,), torch.tensor(g), torch.tensor(b)).numpy()
        got = normalization.layer_norm(jnp.asarray(x), jnp.asarray(g), jnp.asarray(b))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_lrn_vs_torch(self):
        rng = np.random.RandomState(12)
        x = rng.randn(2, 8, 4, 4).astype(np.float32)
        want = F.local_response_norm(torch.tensor(x), 5, alpha=1e-4, beta=0.75, k=1.0).numpy()
        # torch divides alpha by n; ours uses raw alpha like TF/DL4J
        got = normalization.lrn(jnp.asarray(x), depth=5, alpha=1e-4 / 5, beta=0.75, bias=1.0)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_dropout_scales(self):
        x = jnp.ones((1000,))
        out = normalization.dropout(x, 0.5, jax.random.PRNGKey(0))
        assert abs(float(jnp.mean(out)) - 1.0) < 0.1
        np.testing.assert_allclose(normalization.dropout(x, 0.5, jax.random.PRNGKey(0), train=False), x)


class TestAttention:
    def test_mha_vs_torch(self):
        rng = np.random.RandomState(13)
        B, T, E, H = 2, 5, 8, 2
        x = rng.randn(B, T, E).astype(np.float32)
        wq, wk, wv, wo = (rng.randn(E, E).astype(np.float32) * 0.2 for _ in range(4))
        m = torch.nn.MultiheadAttention(E, H, bias=False, batch_first=True)
        with torch.no_grad():
            m.in_proj_weight.copy_(torch.tensor(np.concatenate([wq.T, wk.T, wv.T])))
            m.out_proj.weight.copy_(torch.tensor(wo.T))
        want, _ = m(torch.tensor(x), torch.tensor(x), torch.tensor(x))
        got = attn.multi_head_attention(jnp.asarray(x), jnp.asarray(x),
                                        jnp.asarray(wq), jnp.asarray(wk),
                                        jnp.asarray(wv), jnp.asarray(wo), num_heads=H)
        np.testing.assert_allclose(got, want.detach().numpy(), rtol=1e-4, atol=1e-5)

    def test_flash_matches_exact(self):
        rng = np.random.RandomState(14)
        B, T, H, D = 2, 33, 2, 4
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        exact = attn.dot_product_attention(q, k, v)
        flash = attn.flash_attention(q, k, v, block_size=8)
        np.testing.assert_allclose(flash, exact, rtol=1e-4, atol=1e-5)

    def test_flash_causal_matches_exact(self):
        rng = np.random.RandomState(15)
        B, T, H, D = 1, 17, 1, 4
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        exact = attn.dot_product_attention(q, k, v, is_causal=True)
        flash = attn.flash_attention(q, k, v, is_causal=True, block_size=5)
        np.testing.assert_allclose(flash, exact, rtol=1e-4, atol=1e-5)


class TestLosses:
    def test_mse_matches_torch(self):
        rng = np.random.RandomState(16)
        y = rng.randn(4, 3).astype(np.float32)
        p = rng.randn(4, 3).astype(np.float32)
        want = F.mse_loss(torch.tensor(p), torch.tensor(y)).numpy()
        got = losses.mse(jnp.asarray(y), jnp.asarray(p))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_softmax_xent_matches_torch(self):
        rng = np.random.RandomState(17)
        logits = rng.randn(5, 4).astype(np.float32)
        labels = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 5)]
        want = F.cross_entropy(torch.tensor(logits), torch.tensor(labels.argmax(1))).numpy()
        got = losses.softmax_cross_entropy_logits(jnp.asarray(labels), jnp.asarray(logits))
        np.testing.assert_allclose(got, want, rtol=1e-5)
        got_sparse = losses.sparse_mcxent(jnp.asarray(labels.argmax(1)), jnp.asarray(logits))
        np.testing.assert_allclose(got_sparse, want, rtol=1e-5)

    def test_xent_binary(self):
        y = jnp.asarray([[1.0], [0.0]])
        p = jnp.asarray([[0.9], [0.2]])
        want = float(F.binary_cross_entropy(torch.tensor([[0.9], [0.2]]), torch.tensor([[1.0], [0.0]])))
        got = float(losses.xent(y, p))
        assert abs(got - want) < 1e-5

    def test_masked_loss_ignores_masked(self):
        y = jnp.asarray([[1.0, 0.0], [0.5, 0.5]])
        p = jnp.asarray([[0.8, 0.2], [0.0, 1.0]])
        mask = jnp.asarray([1.0, 0.0])
        got = losses.mse(y, p, mask=mask)
        want = losses.mse(y[:1], p[:1])
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_loss_gradcheck(self):
        """Finite-difference check through the loss in fp64, like the
        reference's GradCheckUtil (SURVEY §4 centerpiece)."""
        with jax.experimental.enable_x64():
            rng = np.random.RandomState(18)
            logits = jnp.asarray(rng.randn(3, 4))
            labels = jnp.asarray(np.eye(4)[rng.randint(0, 4, 3)])
            f = lambda lg: losses.softmax_cross_entropy_logits(labels, lg)
            g = jax.grad(f)(logits)
            eps = 1e-6
            for i in range(3):
                for j in range(4):
                    lp = logits.at[i, j].add(eps)
                    lm = logits.at[i, j].add(-eps)
                    fd = (f(lp) - f(lm)) / (2 * eps)
                    np.testing.assert_allclose(g[i, j], fd, rtol=1e-4, atol=1e-7)


class TestRegistry:
    def test_registry_size_and_dispatch(self):
        assert len(registry.all_ops()) > 200
        out = registry.exec_op("add", jnp.ones(3), jnp.ones(3))
        np.testing.assert_allclose(out, 2 * np.ones(3))

    def test_platform_override(self):
        calls = []
        orig = registry.get("relu")
        registry.register_platform_override("relu", lambda x: calls.append(1) or orig(x))
        try:
            registry.exec_op("relu", jnp.asarray([-1.0, 1.0]))
            assert calls == [1]
        finally:
            registry.clear_platform_override("relu")

    def test_nms(self):
        boxes = jnp.asarray([[0, 0, 10, 10], [0, 0, 9, 9], [20, 20, 30, 30]], jnp.float32)
        scores = jnp.asarray([0.9, 0.8, 0.7])
        keep = registry.exec_op("non_max_suppression", boxes, scores, 3, 0.5)
        assert list(np.asarray(keep)) == [0, 2, -1]

    def test_sequence_mask(self):
        m = registry.exec_op("sequence_mask", jnp.asarray([1, 3]), 4)
        np.testing.assert_array_equal(np.asarray(m), [[1, 0, 0, 0], [1, 1, 1, 0]])

    def test_cumsum_exclusive_reverse(self):
        x = jnp.asarray([1.0, 2.0, 3.0])
        np.testing.assert_allclose(registry.exec_op("cumsum", x, 0, True, False), [0, 1, 3])
        np.testing.assert_allclose(registry.exec_op("cumsum", x, 0, False, True), [6, 5, 3])
