"""NDArray / factory tests (ref: nd4j NDArrayTests / NDArrayTestsFortran style)."""

import numpy as np
import pytest

pytestmark = pytest.mark.quick

from deeplearning4j_tpu.linalg import DataType, NDArray, nd


class TestFactory:
    def test_zeros_ones(self):
        z = nd.zeros(2, 3)
        assert z.shape == (2, 3)
        assert z.sumNumber() == 0.0
        o = nd.ones(4)
        assert o.sumNumber() == 4.0

    def test_create_reshape(self):
        a = nd.create([1, 2, 3, 4, 5, 6], shape=(2, 3))
        assert a.shape == (2, 3)
        assert a.getDouble(1, 2) == 6.0

    def test_arange_linspace(self):
        assert nd.arange(5).numpy().tolist() == [0, 1, 2, 3, 4]
        ls = nd.linspace(0, 1, 5)
        np.testing.assert_allclose(ls.numpy(), [0, 0.25, 0.5, 0.75, 1.0], atol=1e-6)

    def test_rng_determinism(self):
        nd.setSeed(42)
        a = nd.randn(3, 3)
        nd.setSeed(42)
        b = nd.randn(3, 3)
        assert a.equals(b)
        c = nd.randn(3, 3)
        assert not b.equals(c)

    def test_rng_state_save_restore(self):
        rng = nd.Random(7)
        _ = nd.rand(2, 2, rng=rng)
        state = rng.getState()
        a = nd.rand(2, 2, rng=rng)
        rng.setState(state)
        b = nd.rand(2, 2, rng=rng)
        assert a.equals(b)

    def test_one_hot(self):
        oh = nd.oneHot([0, 2], 3)
        np.testing.assert_allclose(oh.numpy(), [[1, 0, 0], [0, 0, 1]])


class TestNDArrayOps:
    def test_add_broadcast(self):
        a = nd.ones(2, 3)
        b = nd.create([1, 2, 3])
        c = a.add(b)
        np.testing.assert_allclose(c.numpy(), [[2, 3, 4], [2, 3, 4]])

    def test_inplace_mutation_visible(self):
        a = nd.ones(2, 2)
        alias = a
        a.addi(1.0)
        assert alias.sumNumber() == 8.0

    def test_mmul(self):
        a = nd.create([[1, 2], [3, 4]])
        b = nd.create([[5, 6], [7, 8]])
        c = a.mmul(b)
        np.testing.assert_allclose(c.numpy(), [[19, 22], [43, 50]])

    def test_mmul_transpose_flags(self):
        a = nd.randn(3, 4)
        b = nd.randn(3, 5)
        c = a.mmul(b, transpose_a=True)
        np.testing.assert_allclose(c.numpy(), a.numpy().T @ b.numpy(), atol=1e-5)

    def test_reductions(self):
        a = nd.create([[1.0, 2.0], [3.0, 4.0]])
        assert a.sumNumber() == 10.0
        np.testing.assert_allclose(a.sum(0).numpy(), [4, 6])
        np.testing.assert_allclose(a.mean(1).numpy(), [1.5, 3.5])
        assert a.maxNumber() == 4.0
        assert int(a.argMax(1).numpy()[0]) == 1

    def test_std_is_sample_std(self):
        a = nd.create([1.0, 2.0, 3.0, 4.0])
        assert abs(float(a.std().numpy()) - np.std([1, 2, 3, 4], ddof=1)) < 1e-6

    def test_norms(self):
        a = nd.create([3.0, -4.0])
        assert a.norm2Number() == pytest.approx(5.0)
        assert a.norm1Number() == pytest.approx(7.0)

    def test_view_writeback(self):
        a = nd.zeros(3, 3)
        row = a.getRow(1)
        row.assign(nd.ones(3))
        np.testing.assert_allclose(a.numpy()[1], [1, 1, 1])
        assert a.sumNumber() == 3.0

    def test_putscalar_getrow(self):
        a = nd.zeros(2, 2)
        a.putScalar(0, 1, 5.0)
        assert a.getDouble(0, 1) == 5.0

    def test_setitem(self):
        a = nd.zeros(4)
        a[1:3] = 7.0
        np.testing.assert_allclose(a.numpy(), [0, 7, 7, 0])

    def test_dup_detaches(self):
        a = nd.ones(2)
        b = a.dup()
        b.addi(1.0)
        assert a.sumNumber() == 2.0
        assert b.sumNumber() == 4.0

    def test_cast(self):
        a = nd.create([1.7, 2.3])
        b = a.castTo(DataType.INT32)
        assert b.dtype == DataType.INT32
        assert b.numpy().tolist() == [1, 2]

    def test_transpose_permute(self):
        a = nd.randn(2, 3, 4)
        assert a.permute(2, 0, 1).shape == (4, 2, 3)
        # no-args transpose reverses ALL dims (ref: INDArray.transpose)
        assert a.transpose().shape == (4, 3, 2)

    def test_view_reads_through_base_mutation(self):
        a = nd.zeros(3, 3)
        row = a.getRow(1)
        a.addi(1.0)
        np.testing.assert_allclose(row.numpy(), [1, 1, 1])
        row.addi(1.0)  # must compute from fresh base data
        np.testing.assert_allclose(a.numpy()[1], [2, 2, 2])
        np.testing.assert_allclose(a.numpy()[0], [1, 1, 1])

    def test_sibling_views_no_clobber(self):
        a = nd.zeros(2, 2)
        r0, r1 = a.getRow(0), a.getRow(1)
        r0.assign(nd.ones(2))
        r1.assign(nd.create([2.0, 2.0]))
        np.testing.assert_allclose(a.numpy(), [[1, 1], [2, 2]])
        np.testing.assert_allclose(r0.numpy(), [1, 1])

    def test_argmax_multi_dims(self):
        a = nd.arange(24).reshape(2, 3, 4)
        am = a.argMax(1, 2)
        assert am.shape == (2,)
        assert am.numpy().tolist() == [11, 11]

    def test_shuffle_inplace(self):
        a = nd.arange(16).reshape(8, 2)
        before = a.numpy().copy()
        ret = nd.shuffle(a)
        assert ret is a
        assert sorted(a.numpy()[:, 0].tolist()) == sorted(before[:, 0].tolist())

    def test_inplace_shape_mismatch_raises(self):
        a = nd.ones(1, 3)
        with pytest.raises(ValueError, match="cannot change shape"):
            a.addi(nd.ones(2, 3))

    def test_concat_stack(self):
        a, b = nd.ones(2, 2), nd.zeros(2, 2)
        assert nd.concat(0, a, b).shape == (4, 2)
        assert nd.concat(1, a, b).shape == (2, 4)
        assert nd.stack(0, a, b).shape == (2, 2, 2)

    def test_comparisons(self):
        a = nd.create([1.0, 5.0, 3.0])
        mask = a.gt(2.0)
        assert mask.dtype == DataType.BOOL
        assert mask.numpy().tolist() == [False, True, True]

    def test_tensor_along_dimension(self):
        a = nd.arange(24).reshape(2, 3, 4)
        t = a.tensorAlongDimension(0, 1, 2)
        assert t.shape == (3, 4)
        np.testing.assert_allclose(t.numpy(), a.numpy()[0])

    def test_operator_overloads(self):
        a = nd.create([2.0, 4.0])
        np.testing.assert_allclose((a + 1).numpy(), [3, 5])
        np.testing.assert_allclose((1 - a).numpy(), [-1, -3])
        np.testing.assert_allclose((a / 2).numpy(), [1, 2])
        np.testing.assert_allclose((a @ nd.create([[1.0], [1.0]])).numpy(), [6])


class TestEnvironment:
    def test_registry_describe(self):
        from deeplearning4j_tpu.utils.environment import Environment, KNOBS
        env = Environment.get()
        desc = env.describe()
        for knob in KNOBS:
            assert knob in desc


class TestWidenedSurface:
    """Round-3 INDArray surface widening (VERDICT r2 weak #7): vector
    broadcast ops, distances, entropy, conditions, Transforms statics."""

    def test_row_column_vector_ops(self):
        a = nd.create(np.arange(6, dtype=np.float32).reshape(2, 3))
        np.testing.assert_allclose(
            a.addRowVector([10, 20, 30]).numpy(),
            [[10, 21, 32], [13, 24, 35]])
        np.testing.assert_allclose(
            a.mulColumnVector([2, 3]).numpy(), [[0, 2, 4], [9, 12, 15]])
        b = nd.create(np.ones((2, 3), np.float32))
        b.subiRowVector([1, 1, 1])
        np.testing.assert_allclose(b.numpy(), np.zeros((2, 3)))

    def test_inplace_through_view(self):
        a = nd.create(np.zeros((3, 3), np.float32))
        row = a.getRow(1)
        row.addiRowVector([1, 2, 3])
        np.testing.assert_allclose(a.numpy()[1], [1, 2, 3])
        np.testing.assert_allclose(a.numpy()[0], 0)

    def test_distances_and_entropy(self):
        a = nd.create(np.asarray([3.0, 4.0], np.float32))
        b = nd.create(np.asarray([0.0, 0.0], np.float32))
        assert a.distance2(b) == pytest.approx(5.0)
        assert a.distance1(b) == pytest.approx(7.0)
        assert a.squaredDistance(b) == pytest.approx(25.0)
        p = nd.create(np.asarray([0.5, 0.5], np.float32))
        assert p.shannonEntropy() == pytest.approx(1.0, abs=1e-5)

    def test_abs_reductions_and_sort(self):
        a = nd.create(np.asarray([[-5.0, 2.0], [3.0, -1.0]], np.float32))
        assert a.amaxNumber() == 5.0
        assert a.aminNumber() == 1.0
        np.testing.assert_allclose(a.sort(dim=1).numpy(),
                                   [[-5, 2], [-1, 3]])
        np.testing.assert_allclose(a.sort(dim=1, ascending=False).numpy(),
                                   [[2, -5], [3, -1]])
        assert a.maxIndex() == 2

    def test_conditions_and_boolean_indexing(self):
        from deeplearning4j_tpu.linalg.conditions import (BooleanIndexing,
                                                          Conditions)
        a = nd.create(np.asarray([1.0, -2.0, 3.0, np.nan], np.float32))
        assert BooleanIndexing.countOccurrences(
            a, Conditions.greaterThan(0.0)) == 2
        assert BooleanIndexing.firstIndex(a, Conditions.isNan()) == 3
        a.replaceWhere(0.0, Conditions.isNan())
        np.testing.assert_allclose(a.numpy(), [1, -2, 3, 0])
        a.replaceWhere(9.0, Conditions.lessThan(0.0) | Conditions.equals(3.0))
        np.testing.assert_allclose(a.numpy(), [1, 9, 9, 0])

    def test_transforms_statics(self):
        from deeplearning4j_tpu.linalg import transforms as T
        x = nd.create(np.asarray([-1.0, 0.0, 1.0], np.float32))
        np.testing.assert_allclose(T.relu(x).numpy(), [0, 0, 1])
        np.testing.assert_allclose(
            T.sigmoid(x).numpy(), 1 / (1 + np.exp([1.0, 0.0, -1.0])),
            rtol=1e-5)
        assert T.cosineSim(x, x) == pytest.approx(1.0)
        u = T.unitVec(nd.create(np.asarray([3.0, 4.0], np.float32)))
        np.testing.assert_allclose(u.numpy(), [0.6, 0.8], rtol=1e-6)
        d = T.allEuclideanDistances(
            nd.create(np.eye(2, dtype=np.float32)),
            nd.create(np.eye(2, dtype=np.float32)))
        np.testing.assert_allclose(np.diag(d.numpy()), 0, atol=1e-6)
        assert T.Transforms.euclideanDistance([0, 0], [3, 4]) == 5.0

    def test_conversions_and_layout_shims(self):
        a = nd.create(np.arange(4, dtype=np.float32).reshape(2, 2))
        assert a.toIntVector().tolist() == [0, 1, 2, 3]
        assert a.toDoubleMatrix().dtype == np.float64
        assert a.ordering() == "c"
        assert a.stride() == (2, 1)


class TestR4Surface:
    """r4 NDArray surface push (VERDICT r3 #9): behavior checks for the
    new families + an inventory gate against a checked-in method list."""

    def test_new_unaries_and_inplace(self):
        from deeplearning4j_tpu.linalg.ndarray import NDArray
        x = NDArray(np.asarray([0.25, 0.5], np.float32))
        np.testing.assert_allclose(np.asarray(x.asin().jax()),
                                   np.arcsin([0.25, 0.5]), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(x.oneMinus().jax()),
                                   [0.75, 0.5])
        y = NDArray(np.asarray([4.0, 9.0], np.float32))
        y.rsqrti()
        np.testing.assert_allclose(np.asarray(y.jax()), [0.5, 1 / 3],
                                   rtol=1e-6)

    def test_rsub_rdiv_vectors(self):
        from deeplearning4j_tpu.linalg.ndarray import NDArray
        m = NDArray(np.asarray([[2.0, 4.0], [8.0, 16.0]], np.float32))
        r = m.rdivRowVector(np.asarray([2.0, 4.0], np.float32))
        np.testing.assert_allclose(np.asarray(r.jax()),
                                   [[1.0, 1.0], [0.25, 0.25]])
        c = m.rsubColumnVector(np.asarray([1.0, 2.0], np.float32))
        np.testing.assert_allclose(np.asarray(c.jax()),
                                   [[-1.0, -3.0], [-6.0, -14.0]])

    def test_inplace_comparisons(self):
        from deeplearning4j_tpu.linalg.ndarray import NDArray
        x = NDArray(np.asarray([1.0, 5.0, 3.0], np.float32))
        x.gti(2.0)
        np.testing.assert_allclose(np.asarray(x.jax()), [0.0, 1.0, 1.0])
        assert x.dtype.name.lower().startswith("float")

    def test_matrix_and_stats(self):
        from deeplearning4j_tpu.linalg.ndarray import NDArray
        m = NDArray(np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32))
        assert m.trace() == 5.0
        np.testing.assert_allclose(np.asarray(m.diag().jax()), [1.0, 4.0])
        v = NDArray(np.asarray([1.0, 2.0], np.float32))
        np.testing.assert_allclose(np.asarray(v.outer(v).jax()),
                                   [[1.0, 2.0], [2.0, 4.0]])
        rng = np.random.RandomState(0)
        z = NDArray(rng.randn(1000).astype(np.float32))
        assert abs(float(z.skewness().jax())) < 0.3
        assert abs(float(z.kurtosis().jax())) < 0.5

    def test_shape_and_views(self):
        from deeplearning4j_tpu.linalg.ndarray import NDArray
        x = NDArray(np.arange(6, dtype=np.float32))
        x.reshapei(2, 3)
        assert x.shape == (2, 3)
        x.transposei()
        assert x.shape == (3, 2)
        assert x.moveAxis(0, 1).shape == (2, 3)
        assert x.repmat(2, 2).shape == (6, 4)
        assert x.broadcastTo(5, 3, 2).shape == (5, 3, 2)
        m = NDArray(np.arange(12, dtype=np.float32).reshape(3, 4))
        np.testing.assert_allclose(np.asarray(m.getRows(0, 2).jax()),
                                   np.asarray(m.jax())[[0, 2]])
        np.testing.assert_allclose(np.asarray(m.getColumns(1, 3).jax()),
                                   np.asarray(m.jax())[:, [1, 3]])
        m.putSlice(1, np.zeros(4, np.float32))
        assert float(m.sumNumber()) == float(np.arange(12).sum()
                                             - (4 + 5 + 6 + 7))

    def test_where_and_argsort(self):
        from deeplearning4j_tpu.linalg.conditions import Conditions
        from deeplearning4j_tpu.linalg.ndarray import NDArray
        x = NDArray(np.asarray([3.0, -1.0, 5.0, 0.0], np.float32))
        got = np.asarray(x.getWhere(None, Conditions.greaterThan(0)).jax())
        np.testing.assert_allclose(got, [3.0, 5.0])
        masked = x.putWhereWithMask(np.asarray([1, 0, 1, 0], np.float32),
                                    np.zeros(4, np.float32))
        np.testing.assert_allclose(np.asarray(masked.jax()),
                                   [0.0, -1.0, 0.0, 0.0])
        np.testing.assert_allclose(np.asarray(x.argsort().jax()),
                                   [1, 3, 0, 2])
        np.testing.assert_allclose(
            np.asarray(x.argsort(descending=True).jax()), [2, 0, 3, 1])

    def test_alloc_alikes_and_workspace_identities(self):
        from deeplearning4j_tpu.linalg.ndarray import NDArray
        x = NDArray(np.ones((2, 3), np.float32))
        assert x.like().shape == (2, 3)
        assert float(x.like().sumNumber()) == 0.0
        assert x.detach() is x and x.leverage() is x and x.migrate() is x

    def test_method_inventory(self):
        """Inventory gate: the surface must keep >= 260 public methods and
        every name in the checked-in core list must exist."""
        from deeplearning4j_tpu.linalg.ndarray import NDArray
        meths = {m for m in dir(NDArray) if not m.startswith("_")}
        assert len(meths) >= 260, len(meths)
        core = {
            # arithmetic + i-variants
            "add", "addi", "sub", "subi", "mul", "muli", "div", "divi",
            "rsub", "rsubi", "rdiv", "rdivi", "pow", "powi", "neg", "negi",
            "fmod", "fmodi", "remainder", "remainderi",
            # broadcast vectors (4 ops x row/col x i)
            "addRowVector", "addiRowVector", "addColumnVector",
            "addiColumnVector", "subRowVector", "mulRowVector",
            "divRowVector", "rsubRowVector", "rdivRowVector",
            "rsubColumnVector", "rdivColumnVector", "rdiviColumnVector",
            # comparisons
            "gt", "gte", "lt", "lte", "eq", "neq", "gti", "gtei", "lti",
            "ltei", "eqi", "neqi",
            # reductions
            "sum", "mean", "max", "min", "prod", "std", "var", "norm1",
            "norm2", "normMax", "normMaxNumber", "amax", "amin", "amean",
            "argMax", "argMin", "cumsum", "cumprod", "cumsumi", "cumprodi",
            "entropy", "logEntropy", "shannonEntropy", "logSumExp",
            "skewness", "kurtosis", "median", "percentile",
            # elementwise
            "abs", "exp", "log", "log2", "log10", "log1p", "expm1", "sqrt",
            "cbrt", "rsqrt", "square", "cube", "reciprocal", "sign",
            "floor", "ceil", "round", "rint", "trunc", "frac", "oneMinus",
            "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
            "tanh", "asinh", "acosh", "atanh", "erf", "erfc", "sigmoid",
            "relu", "elu", "selu", "gelu", "swish", "mish", "softplus",
            "softsign", "hardSigmoid", "hardTanh", "leakyRelu", "clip",
            # linalg / matrix
            "mmul", "mmuli", "dot", "outer", "diag", "trace",
            # shape
            "reshape", "reshapei", "transpose", "transposei", "permute",
            "permutei", "moveAxis", "swapAxes", "expandDims", "squeeze",
            "flatten", "ravel", "tile", "repmat", "repeat", "broadcast",
            "broadcastTo", "reverse", "sort", "argsort",
            # access
            "getRow", "getColumn", "getRows", "getColumns", "getScalar",
            "getDouble", "getFloat", "getInt", "getLong", "putScalar",
            "put", "putRow", "putColumn", "putSlice", "putWhere",
            "putWhereWithMask", "getWhere", "replaceWhere",
            "tensorAlongDimension", "slice_",
            # meta / conversion
            "shape", "rank", "length", "size", "stride", "ordering",
            "dataType", "castTo", "dup", "like", "ulike", "detach",
            "leverage", "migrate", "data", "numpy", "jax", "isView",
            "isScalar", "isVector", "isMatrix", "isRowVector",
            "isColumnVector", "isSquare", "isEmpty", "isNaN", "isInfinite",
            "toFloatVector", "toDoubleVector", "toIntVector",
            "toLongVector", "toFloatMatrix", "toDoubleMatrix",
            "toIntMatrix", "toLongMatrix", "toByteVector", "equalsWithEps",
        }
        missing = core - meths
        assert not missing, f"missing INDArray methods: {sorted(missing)}"


class TestR5SurfaceCompletion:
    """The last INDArray names (ref surface ~300): slices, eps masks,
    along-dimension reducers, cond, percentile, cosineSim, negatives."""

    def test_new_methods(self):
        from deeplearning4j_tpu.linalg.conditions import Conditions
        a = nd.create(np.asarray([[1., -2., 3.], [4., -5., 6.]], np.float32))
        assert float(np.asarray(a.asum().numpy())) == 21.0
        assert a.normmaxNumber() == 6.0
        assert abs(a.percentileNumber(50) - 2.0) < 1e-5
        b = nd.create(np.asarray([[1., -2., 3.], [4., -5., 6.]], np.float32))
        assert a.cosineSim(b) > 0.999
        assert bool(np.asarray(a.eps(b).numpy()).all())
        np.testing.assert_allclose(np.asarray(a.slice(1).numpy()),
                                   [4., -5., 6.])
        np.testing.assert_allclose(np.asarray(a.slice(0, dim=1).numpy()),
                                   [1., 4.])
        assert a.subArray((0, 1), (2, 2)).shape == (2, 2)
        assert a.tensorsAlongDimension(1) == 2
        assert a.vectorsAlongDimension(0) == 3
        m = a.cond(Conditions.greaterThan(0))
        assert float(np.asarray(m.numpy()).sum()) == 4.0
        assert float(np.asarray(a.negative().numpy())[0, 0]) == -1.0
        a2 = nd.create(np.ones((2, 2), np.float32))
        a2.negativei()
        assert float(np.asarray(a2.numpy())[0, 0]) == -1.0
        assert a.close() is None
        np.testing.assert_allclose(
            np.asarray(a.sumAlongDimension(0).numpy()), [5., -7., 9.])
        np.testing.assert_allclose(
            np.asarray(a.meanAlongDimension(1).numpy()),
            [2 / 3, 5 / 3], rtol=1e-5)
