"""NLP subsystem tests (ref: deeplearning4j-nlp test shapes: tokenizer
unit tests, Word2Vec sanity on a structured corpus, serializer
round-trip — SURVEY.md §2.2 "Aux NLP")."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (CommonPreprocessor,
                                    DefaultTokenizerFactory,
                                    NGramTokenizerFactory, ParagraphVectors,
                                    Word2Vec, WordVectorSerializer)


def _corpus(n_sent=300, seed=0):
    """Two topic clusters with disjoint vocabularies: co-occurrence alone
    must pull same-topic words together."""
    rng = np.random.RandomState(seed)
    animals = ["cat", "dog", "horse", "sheep", "cow"]
    tech = ["cpu", "gpu", "tpu", "ram", "disk"]
    sents = []
    for _ in range(n_sent):
        pool = animals if rng.rand() < 0.5 else tech
        sents.append(" ".join(rng.choice(pool, 6)))
    return sents, animals, tech


class TestTokenization:
    def test_default_tokenizer_with_preprocessor(self):
        tf = DefaultTokenizerFactory()
        tf.setTokenPreProcessor(CommonPreprocessor())
        toks = tf.create("The QUICK, brown fox (2024)!").getTokens()
        assert toks == ["the", "quick", "brown", "fox"]

    def test_ngram_tokenizer(self):
        tf = NGramTokenizerFactory(2)
        toks = tf.create("a b c d").getTokens()
        assert toks == ["a b", "b c", "c d"]


class TestWord2Vec:
    @pytest.fixture(scope="class")
    def model(self):
        sents, animals, tech = _corpus()
        m = (Word2Vec.Builder()
             .minWordFrequency(2).layerSize(24).windowSize(3)
             .negativeSample(4).learningRate(0.3).epochs(25)
             .batchSize(256).seed(7)
             .iterate(sents)
             .tokenizerFactory(DefaultTokenizerFactory())
             .build())
        m.fit()
        return m, animals, tech

    def test_vocab_built(self, model):
        m, animals, tech = model
        for w in animals + tech:
            assert m.hasWord(w)
        assert m.getWordVector("cat").shape == (24,)

    def test_topic_clusters_separate(self, model):
        """Same-topic similarity must dominate cross-topic similarity."""
        m, animals, tech = model
        same, cross = [], []
        for a in animals:
            for b in animals:
                if a != b:
                    same.append(m.similarity(a, b))
            for t in tech:
                cross.append(m.similarity(a, t))
        assert np.mean(same) > np.mean(cross) + 0.2, \
            (np.mean(same), np.mean(cross))

    def test_words_nearest(self, model):
        m, animals, tech = model
        near = m.wordsNearest("cat", 4)
        assert len(set(near) & set(animals)) >= 3, near

    def test_serializer_roundtrip(self, model, tmp_path):
        m, animals, _ = model
        p = str(tmp_path / "vecs.txt")
        WordVectorSerializer.writeWord2VecModel(m, p)
        m2 = WordVectorSerializer.readWord2VecModel(p)
        for w in animals:
            np.testing.assert_allclose(m2.getWordVector(w),
                                       m.getWordVector(w), atol=1e-5)
        assert m2.similarity("cat", "dog") == pytest.approx(
            m.similarity("cat", "dog"), abs=1e-4)

    def test_cbow_variant_trains(self):
        sents, animals, tech = _corpus(n_sent=120, seed=1)
        m = (Word2Vec.Builder()
             .minWordFrequency(2).layerSize(16).windowSize(3)
             .elementsLearningAlgorithm("CBOW")
             .epochs(2).batchSize(128).seed(3)
             .iterate(sents).build())
        m.fit()
        assert np.isfinite(np.asarray(m.syn0)).all()

    def test_sharded_embeddings_on_mesh(self, model):
        import jax
        from deeplearning4j_tpu.parallel.mesh import DeviceMesh
        m, animals, _ = model
        mesh = DeviceMesh.create(data=2, model=4)
        m.shard_over_mesh(mesh)
        # still queryable; vocab dim spread over the model axis
        assert m.similarity("cat", "dog") == m.similarity("cat", "dog")
        shards = {s.device for s in m.syn0.addressable_shards}
        assert len(shards) == 8

    def test_mesh_sharded_TRAINING_matches_replicated(self):
        """fit() with embeddings dim-sharded over the model axis (VERDICT
        r4 #9): same seed must give the same vectors as replicated
        training, and the tables stay sharded through every update step."""
        import jax
        from deeplearning4j_tpu.parallel.mesh import DeviceMesh
        sents, animals, tech = _corpus()

        def build(mesh):
            return (Word2Vec.Builder()
                    .minWordFrequency(2).layerSize(32).windowSize(3)
                    .negativeSample(4).learningRate(0.3).epochs(4)
                    .batchSize(128).seed(11)
                    .iterate(sents)
                    .mesh(mesh)
                    .build())

        rep = build(None)
        rep.fit()
        mesh = DeviceMesh.create(data=1, model=8)
        shd = build(mesh)
        shd.fit()
        # tables remained dim-sharded across the training steps
        assert len({s.device for s in shd.syn0.addressable_shards}) == 8
        np.testing.assert_allclose(np.asarray(shd.syn0), np.asarray(rep.syn0),
                                   rtol=2e-4, atol=1e-5)
        s_rep = rep.similarity("cat", "dog")
        s_shd = shd.similarity("cat", "dog")
        np.testing.assert_allclose(s_shd, s_rep, rtol=1e-3)


class TestParagraphVectors:
    def test_doc_vectors_cluster_by_topic(self):
        rng = np.random.RandomState(2)
        animals = ["cat", "dog", "horse", "sheep", "cow"]
        tech = ["cpu", "gpu", "tpu", "ram", "disk"]
        sents, labels = [], []
        for i in range(40):
            pool = animals if i % 2 == 0 else tech
            sents.append(" ".join(rng.choice(pool, 8)))
            labels.append(f"DOC_{i}")
        pv = ParagraphVectors(labels=labels, layer_size=16, window_size=3,
                              min_word_frequency=1, negative=4,
                              learning_rate=0.3, epochs=10, batch_size=64,
                              seed=5, sentence_iter=sents)
        pv.fit()
        same, cross = [], []
        for i in range(0, 40, 2):
            for j in range(0, 40, 2):
                if i != j:
                    same.append(pv.similarityToLabel(f"DOC_{i}", f"DOC_{j}"))
            for j in range(1, 40, 2):
                cross.append(pv.similarityToLabel(f"DOC_{i}", f"DOC_{j}"))
        assert np.mean(same) > np.mean(cross) + 0.15, \
            (np.mean(same), np.mean(cross))
        assert pv.getDocVector("DOC_3").shape == (16,)
