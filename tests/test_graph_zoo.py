"""ComputationGraph + model zoo + object detection tests.

Reference test-strategy parity (SURVEY.md §4): zoo tests instantiate each
model and run a tiny forward pass; graph tests check vertices/DAG wiring;
YOLO loss/NMS sanity.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import (ComputationGraph, ElementWiseVertex,
                                         L2NormalizeVertex, MergeVertex,
                                         SubsetVertex)
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                          OutputLayer)
from deeplearning4j_tpu.nn.objdetect import (DetectedObject, Yolo2OutputLayer,
                                             YoloUtils)
from deeplearning4j_tpu.models import zoo
from deeplearning4j_tpu.train import updaters


class TestComputationGraph:
    def _skip_graph(self):
        """x -> dense1 -> dense2 -> add(dense1) -> out (residual)."""
        g = (NeuralNetConfiguration.Builder().seed(7)
             .updater(updaters.Adam(0.05))
             .graphBuilder()
             .addInputs("x")
             .setInputTypes(InputType.feedForward(4)))
        g.addLayer("d1", DenseLayer(nOut=8, activation="relu"), "x")
        g.addLayer("d2", DenseLayer(nOut=8, activation="relu"), "d1")
        g.addVertex("add", ElementWiseVertex("Add"), "d1", "d2")
        g.addLayer("out", OutputLayer(nOut=3, lossFunction="mcxent",
                                      activation="softmax"), "add")
        g.setOutputs("out")
        return ComputationGraph(g.build())

    def test_forward_and_shapes(self):
        net = self._skip_graph().init()
        out = net.output(np.zeros((5, 4), np.float32))
        assert out.shape == (5, 3)

    def test_training_converges(self):
        rng = np.random.RandomState(0)
        x = rng.randn(90, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 90)]
        x += 2.0 * y @ np.asarray([[1, 0, 0, 0], [0, 1, 0, 0], [0, 0, 1, 0]],
                                  np.float32)
        net = self._skip_graph().init()
        it = ListDataSetIterator(DataSet(x, y), 32, shuffle=True)
        net.fit(it, epochs=20)
        ev = net.evaluate(ListDataSetIterator(DataSet(x, y), 64))
        assert ev.accuracy() > 0.9, ev.stats()

    def test_merge_and_subset_vertices(self):
        g = (NeuralNetConfiguration.Builder().seed(1)
             .updater(updaters.Sgd(0.1))
             .graphBuilder()
             .addInputs("x")
             .setInputTypes(InputType.feedForward(4)))
        g.addLayer("a", DenseLayer(nOut=6, activation="relu"), "x")
        g.addLayer("b", DenseLayer(nOut=4, activation="relu"), "x")
        g.addVertex("cat", MergeVertex(), "a", "b")       # 10
        g.addVertex("sub", SubsetVertex(0, 4), "cat")     # 5
        g.addLayer("out", OutputLayer(nOut=2, lossFunction="mcxent",
                                      activation="softmax"), "sub")
        g.setOutputs("out")
        net = ComputationGraph(g.build()).init()
        assert net.conf.types["cat"].arrayElementsPerExample() == 10
        out = net.output(np.zeros((2, 4), np.float32))
        assert out.shape == (2, 2)

    def test_l2_normalize_vertex(self):
        v = L2NormalizeVertex()
        x = jnp.asarray([[3.0, 4.0]])
        np.testing.assert_allclose(np.asarray(v.apply(x)), [[0.6, 0.8]], rtol=1e-6)

    def test_multiple_outputs(self):
        g = (NeuralNetConfiguration.Builder().seed(2)
             .updater(updaters.Adam(0.05))
             .graphBuilder()
             .addInputs("x")
             .setInputTypes(InputType.feedForward(4)))
        g.addLayer("trunk", DenseLayer(nOut=8, activation="relu"), "x")
        g.addLayer("out1", OutputLayer(nOut=2, lossFunction="mcxent",
                                       activation="softmax"), "trunk")
        g.addLayer("out2", OutputLayer(nOut=1, lossFunction="mse",
                                       activation="identity"), "trunk")
        g.setOutputs("out1", "out2")
        net = ComputationGraph(g.build()).init()
        o1, o2 = net.output(np.zeros((3, 4), np.float32))
        assert o1.shape == (3, 2) and o2.shape == (3, 1)
        from deeplearning4j_tpu.data import MultiDataSet
        rng = np.random.RandomState(0)
        mds = MultiDataSet([rng.randn(16, 4).astype(np.float32)],
                           [np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)],
                            rng.randn(16, 1).astype(np.float32)])
        first = None
        for _ in range(15):
            net.fit(mds)
            first = first if first is not None else net.score()
        assert net.score() < first

    def test_save_load_roundtrip(self, tmp_path):
        net = self._skip_graph().init()
        rng = np.random.RandomState(3)
        ds = DataSet(rng.randn(16, 4).astype(np.float32),
                     np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)])
        net.fit(ds)
        path = str(tmp_path / "graph.zip")
        net.save(path)
        net2 = ComputationGraph.load(path)
        x = ds.features[:4]
        np.testing.assert_allclose(np.asarray(net.output(x)),
                                   np.asarray(net2.output(x)), rtol=1e-6)
        net.fit(ds)
        net2.fit(ds)
        np.testing.assert_allclose(net.score(), net2.score(), rtol=1e-5)


class TestZoo:
    @pytest.mark.parametrize("model_cls,kwargs,in_shape", [
        (zoo.LeNet, {"num_classes": 10}, None),
        (zoo.SimpleCNN, {"num_classes": 5, "input_shape": (3, 32, 32)}, None),
        (zoo.AlexNet, {"num_classes": 10, "input_shape": (3, 96, 96)}, None),
        (zoo.VGG16, {"num_classes": 10, "input_shape": (3, 64, 64)}, None),
        (zoo.VGG19, {"num_classes": 10, "input_shape": (3, 64, 64)}, None),
        (zoo.Darknet19, {"num_classes": 10, "input_shape": (3, 64, 64)}, None),
    ])
    def test_mln_models_forward(self, model_cls, kwargs, in_shape):
        net = model_cls(seed=42, **kwargs).init()
        c, h, w = kwargs.get("input_shape", (1, 28, 28))
        if model_cls is zoo.LeNet:
            x = np.zeros((2, c * h * w), np.float32)
        else:
            x = np.zeros((2, c, h, w), np.float32)
        out = net.output(x)
        assert out.shape == (2, kwargs["num_classes"])
        assert np.allclose(np.asarray(out).sum(1), 1.0, atol=1e-4)

    @pytest.mark.parametrize("model_cls,kwargs", [
        (zoo.ResNet50, {"num_classes": 7, "input_shape": (3, 64, 64)}),
        (zoo.SqueezeNet, {"num_classes": 7, "input_shape": (3, 64, 64)}),
        (zoo.FaceNetNN4Small2, {"num_classes": 7, "input_shape": (3, 64, 64)}),
        (zoo.InceptionResNetV1, {"num_classes": 7, "input_shape": (3, 96, 96)}),
        (zoo.NASNet, {"num_classes": 7, "input_shape": (3, 64, 64)}),
    ])
    def test_graph_models_forward(self, model_cls, kwargs):
        net = model_cls(seed=42, **kwargs).init()
        c, h, w = kwargs["input_shape"]
        out = net.output(np.zeros((2, c, h, w), np.float32))
        assert out.shape == (2, kwargs["num_classes"])

    def test_unet_output_is_map(self):
        net = zoo.UNet(input_shape=(3, 32, 32)).init()
        out = net.output(np.zeros((1, 3, 32, 32), np.float32))
        assert out.shape == (1, 1, 32, 32)
        assert (np.asarray(out) >= 0).all() and (np.asarray(out) <= 1).all()

    def test_xception_forward(self):
        net = zoo.Xception(num_classes=4, input_shape=(3, 71, 71), seed=1).init()
        out = net.output(np.zeros((1, 3, 71, 71), np.float32))
        assert out.shape == (1, 4)

    def test_text_generation_lstm(self):
        m = zoo.TextGenerationLSTM(vocab_size=30)
        net = m.init()
        out = net.output(np.zeros((2, 30, 60), np.float32))
        assert out.shape == (2, 30, 60)

    def test_resnet50_bottleneck_count(self):
        net = zoo.ResNet50(num_classes=3, input_shape=(3, 64, 64)).init()
        conv_names = [n.name for n in net.conf.topo if "c3" in n.name]
        assert len(conv_names) == 3 + 4 + 6 + 3  # bottlenecks per stage


class TestYolo:
    def _tiny_net(self, grid=4, n_classes=2, n_boxes=2):
        anchors = [[1.0, 1.0], [2.0, 2.0]][:n_boxes]
        conf = (NeuralNetConfiguration.Builder().seed(5)
                .updater(updaters.Adam(1e-3)).list()
                .layer(ConvolutionLayer(kernelSize=(3, 3), padding=(1, 1),
                                        nOut=16, activation="relu"))
                .layer(ConvolutionLayer(kernelSize=(1, 1),
                                        nOut=n_boxes * (5 + n_classes),
                                        activation="identity"))
                .layer(Yolo2OutputLayer(boundingBoxPriors=anchors))
                .setInputType(InputType.convolutional(grid, grid, 3))
                .build())
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()

    def _labels(self, n, grid, n_classes):
        lab = np.zeros((n, 4 + n_classes, grid, grid), np.float32)
        # one object per example in cell (1,1): box from (0.8,0.9)->(1.6,1.9)
        lab[:, 0, 1, 1] = 0.8
        lab[:, 1, 1, 1] = 0.9
        lab[:, 2, 1, 1] = 1.6
        lab[:, 3, 1, 1] = 1.9
        lab[:, 4, 1, 1] = 1.0  # class 0
        return lab

    def test_yolo_loss_decreases(self):
        grid, n_classes = 4, 2
        net = self._tiny_net(grid, n_classes)
        rng = np.random.RandomState(0)
        x = rng.rand(8, 3, grid, grid).astype(np.float32)
        ds = DataSet(x, self._labels(8, grid, n_classes))
        first = None
        for _ in range(25):
            net.fit(ds)
            if first is None:
                first = net.score()
        assert np.isfinite(net.score())
        assert net.score() < first

    def test_yolo_forward_activations(self):
        grid, n_classes, n_boxes = 4, 2, 2
        net = self._tiny_net(grid, n_classes, n_boxes)
        out = np.asarray(net.output(np.zeros((1, 3, grid, grid), np.float32)))
        out = out.reshape(1, n_boxes, 5 + n_classes, grid, grid)
        assert (out[:, :, 0:2] >= 0).all() and (out[:, :, 0:2] <= 1).all()  # xy
        assert (out[:, :, 2:4] > 0).all()                                   # wh
        assert (out[:, :, 4] >= 0).all() and (out[:, :, 4] <= 1).all()      # conf
        np.testing.assert_allclose(out[:, :, 5:].sum(2), 1.0, atol=1e-5)    # cls

    def test_yolo_utils_nms(self):
        a = DetectedObject(0, 1.0, 1.0, 1.0, 1.0, 0, 0.9)
        b = DetectedObject(0, 1.05, 1.0, 1.0, 1.0, 0, 0.8)   # overlaps a
        c = DetectedObject(0, 3.0, 3.0, 1.0, 1.0, 0, 0.7)    # separate
        d = DetectedObject(0, 1.0, 1.0, 1.0, 1.0, 1, 0.6)    # other class
        keep = YoloUtils.nms([a, b, c, d], threshold=0.4)
        confs = sorted(o.confidence for o in keep)
        assert confs == [0.6, 0.7, 0.9]

    def test_get_predicted_objects(self):
        grid, n_classes, n_boxes = 4, 2, 1
        out = np.zeros((1, n_boxes * (5 + n_classes), grid, grid), np.float32)
        out = out.reshape(1, n_boxes, 5 + n_classes, grid, grid)
        out[0, 0, 0, 2, 3] = 0.5   # cx offset
        out[0, 0, 1, 2, 3] = 0.5
        out[0, 0, 2, 2, 3] = 1.0   # w
        out[0, 0, 3, 2, 3] = 1.0
        out[0, 0, 4, 2, 3] = 0.95  # conf
        out[0, 0, 5, 2, 3] = 0.9   # class 0
        out[0, 0, 6, 2, 3] = 0.1
        objs = YoloUtils.getPredictedObjects([[1.0, 1.0]],
                                             out.reshape(1, -1, grid, grid),
                                             conf_threshold=0.5)
        assert len(objs) == 1
        o = objs[0]
        assert o.predicted_class == 0
        assert abs(o.center_x - 3.5) < 1e-5 and abs(o.center_y - 2.5) < 1e-5
