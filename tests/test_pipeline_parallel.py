"""Pipeline parallelism: pipelined execution must match single-device
execution exactly — forward, loss, AND the updated parameters after one
train step (SURVEY.md §2.3 PP row; VERDICT r4 #4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models import transformer as tfm
from deeplearning4j_tpu.parallel.mesh import DeviceMesh
from deeplearning4j_tpu.parallel import pipeline as pp
from deeplearning4j_tpu.train import updaters

pytestmark = pytest.mark.quick


@pytest.fixture(scope="module")
def devices8():
    ds = jax.devices()
    if len(ds) < 8:
        pytest.skip("needs 8 virtual devices")
    return ds


def _setup(n_layers=4):
    cfg = tfm.TransformerConfig.tiny(dtype=jnp.float32, causal=True,
                                     n_layers=n_layers)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    B, T = 8, 16
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, T)), jnp.int32)
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, T)), jnp.int32)
    return cfg, params, tokens, targets


class TestPipelineParallel:
    def test_pipeline_loss_matches_single_device(self, devices8):
        cfg, params, tokens, targets = _setup()
        want = float(tfm.loss_fn(params, tokens, targets, cfg))
        mesh = DeviceMesh(jax.sharding.Mesh(
            np.asarray(devices8).reshape(2, 4), ("data", "pipe")))
        pparams = pp.to_pipeline_params(params)
        pparams = jax.tree_util.tree_map(
            jax.device_put, pparams, pp.pipeline_param_shardings(cfg, mesh),
            is_leaf=lambda x: isinstance(x, jax.Array))
        with mesh.mesh:
            got = float(pp.pipeline_loss_fn(pparams, tokens, targets, cfg,
                                            mesh, n_micro=4))
        np.testing.assert_allclose(got, want, rtol=2e-5)

    def test_pipeline_train_step_matches_single_device(self, devices8):
        cfg, params, tokens, targets = _setup()
        updater = updaters.Adam(1e-2)

        # single-device reference step on the SAME math (pipeline layout,
        # trivial 1-stage pipe) vs a real 4-stage pipe
        def run(mesh_shape, names, n_micro):
            n = int(np.prod(mesh_shape))
            mesh = DeviceMesh(jax.sharding.Mesh(
                np.asarray(devices8[:n]).reshape(mesh_shape), names))
            pparams = pp.to_pipeline_params(
                jax.tree_util.tree_map(jnp.copy, params))
            pparams = jax.tree_util.tree_map(
                jax.device_put, pparams,
                pp.pipeline_param_shardings(cfg, mesh),
                is_leaf=lambda x: isinstance(x, jax.Array))
            opt = jax.tree_util.tree_map(
                lambda p: updater.init_state(p.astype(jnp.float32)), pparams,
                is_leaf=lambda x: isinstance(x, jax.Array))
            step = pp.make_pipeline_train_step(cfg, updater, mesh, n_micro)
            with mesh.mesh:
                new_p, _, _, loss = step(pparams, opt,
                                         jnp.asarray(0, jnp.int32),
                                         tokens, targets)
            return float(loss), jax.device_get(new_p)

        loss1, p1 = run((1, 1), ("data", "pipe"), 1)
        loss4, p4 = run((2, 4), ("data", "pipe"), 4)
        np.testing.assert_allclose(loss4, loss1, rtol=2e-5)
        flat1 = jax.tree_util.tree_leaves(p1)
        flat4 = jax.tree_util.tree_leaves(p4)
        for a, b in zip(flat1, flat4):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-4, atol=1e-5)

    def test_pipeline_vs_unpipelined_forward_math(self, devices8):
        """The pipeline block math itself (stacked scan) must equal the
        reference layer loop — catches drift between _block and
        models.transformer.forward."""
        cfg, params, tokens, targets = _setup(n_layers=2)
        want = float(tfm.loss_fn(params, tokens, targets, cfg))
        mesh = DeviceMesh(jax.sharding.Mesh(
            np.asarray(devices8[:2]).reshape(1, 2), ("data", "pipe")))
        pparams = pp.to_pipeline_params(params)
        with mesh.mesh:
            got = float(pp.pipeline_loss_fn(pparams, tokens, targets, cfg,
                                            mesh, n_micro=2))
        np.testing.assert_allclose(got, want, rtol=2e-5)

    def test_microbatch_roundtrip_and_validation(self, devices8):
        x = jnp.arange(24.0).reshape(8, 3)
        m = pp.microbatch(x, 4)
        assert m.shape == (4, 2, 3)
        np.testing.assert_allclose(np.asarray(pp.unmicrobatch(m)),
                                   np.asarray(x))
        with pytest.raises(ValueError, match="not divisible"):
            pp.microbatch(x, 3)
        mesh = DeviceMesh(jax.sharding.Mesh(
            np.asarray(devices8).reshape(1, 8), ("data", "pipe")))
        with pytest.raises(ValueError, match="pipeline depth"):
            pp.pipeline_apply(lambda p, a: a, jnp.zeros((8, 1)),
                              jnp.zeros((2, 1, 4)), mesh)
