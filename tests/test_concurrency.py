"""Concurrency tooling (ISSUE 8), dynamic layers: the seeded
deterministic InterleavingHarness (lost-increment reproduction on the
bad fixture, determinism pins, locked clean bills), the instrumented
lock layer (wait/hold/contention metrics, the lock-order witness), and
one ``-m races`` regression per E201/E202 class fixed in the repo
(serving stats, prefetch error latches, the async checkpoint writer,
stats storage, UIServer lifecycle)."""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu import profiler as prof
from deeplearning4j_tpu.faults import InterleavingHarness, preemptive_stress

races = pytest.mark.races


# ----------------------------------------------------------- bad fixtures
class UnsafeCounter:
    """THE E202 bad fixture: bare read-modify-write on shared state."""

    def __init__(self):
        self.value = 0

    def inc(self):
        self.value += 1


class LockedCounter:
    """The fix: the same increment under a lock."""

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self):
        with self._lock:
            self.value += 1


def _hammer(counter_cls, seed, n=40, threads=2):
    c = counter_cls()

    def body():
        for _ in range(n):
            c.inc()
    InterleavingHarness(seed=seed).run(*([body] * threads))
    return c.value, n * threads


@races
class TestInterleavingHarness:
    def test_reproduces_lost_increment_on_bad_fixture(self):
        """ISSUE 8 acceptance: the harness deterministically reproduces
        the E202-class lost increment on the unfixed fixture."""
        lost_seeds = [s for s in range(6)
                      if _hammer(UnsafeCounter, s)[0] < _hammer(
                          UnsafeCounter, s)[1]]
        assert lost_seeds, "no seed lost an increment — harness is not " \
                           "interleaving inside the read-modify-write"
        # and not flakily: the pinned seed loses on every run
        seed = lost_seeds[0]
        first, expected = _hammer(UnsafeCounter, seed)
        assert first < expected

    def test_schedule_is_deterministic(self):
        for seed in range(4):
            a, _ = _hammer(UnsafeCounter, seed)
            b, _ = _hammer(UnsafeCounter, seed)
            assert a == b, f"seed {seed} produced two different schedules"

    def test_different_seeds_differ(self):
        outcomes = {_hammer(UnsafeCounter, s)[0] for s in range(6)}
        assert len(outcomes) > 1

    def test_locked_fixture_never_loses(self):
        for seed in range(3):
            got, expected = _hammer(LockedCounter, seed, n=15)
            assert got == expected

    def test_three_way_interleaving(self):
        got, expected = _hammer(UnsafeCounter, seed=1, n=25, threads=3)
        assert got <= expected
        again, _ = _hammer(UnsafeCounter, seed=1, n=25, threads=3)
        assert got == again

    def test_results_and_errors_propagate(self):
        h = InterleavingHarness(seed=0)

        def ok():
            return 41 + 1

        def boom():
            raise RuntimeError("body failed")
        assert InterleavingHarness(seed=0).run(ok, ok) == [42, 42]
        with pytest.raises(RuntimeError, match="body failed"):
            h.run(ok, boom)

    def test_sweep_shapes(self):
        out = InterleavingHarness.sweep(
            lambda: [lambda: 1, lambda: 2], seeds=range(2))
        assert out == [[1, 2], [1, 2]]

    def test_timeout_releases_surviving_threads(self):
        # after run() gives up, parked threads must free-run to
        # completion instead of spinning in _wait_for_token forever
        gate = threading.Event()
        done = []

        def stuck():
            gate.wait()             # blocked outside the harness
            done.append("stuck")

        def quick():
            done.append("quick")
        h = InterleavingHarness(seed=0, timeout=1.0)
        with pytest.raises(TimeoutError):
            h.run(stuck, quick)
        gate.set()
        deadline = time.monotonic() + 5.0
        while "stuck" not in done and time.monotonic() < deadline:
            time.sleep(0.01)
        assert "stuck" in done

    def test_bodies_in_randomly_named_files_still_interleave(self, tmp_path):
        # the tracer exclusion is by exact file, not path substring: a
        # user module named like the stdlib must still get switch points
        import importlib.util
        src = tmp_path / "my_random_threading_util.py"
        src.write_text("class Counter:\n"
                       "    def __init__(self):\n"
                       "        self.n = 0\n"
                       "    def bump(self):\n"
                       "        for _ in range(60):\n"
                       "            self.n += 1\n")
        spec = importlib.util.spec_from_file_location("my_rt_util", src)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        lost = False
        for seed in range(10):
            c = mod.Counter()
            InterleavingHarness(seed=seed).run(c.bump, c.bump)
            if c.n < 120:
                lost = True
                break
        assert lost, "no interleaving inside a stdlib-lookalike filename"


@races
class TestErrorLatchRace:
    """Regression for the AsyncDataSetIterator / DevicePrefetcher
    `_pending_error` fix: the first-error latch is exactly-once under
    adversarial interleavings."""

    def test_first_record_wins_and_take_is_exactly_once(self):
        from deeplearning4j_tpu.data.dataset import _ErrorLatch
        e1, e2 = RuntimeError("first"), RuntimeError("second")
        for seed in range(4):
            latch = _ErrorLatch()
            taken = []

            def writer(e):
                def body():
                    latch.record(e)
                return body

            def taker():
                taken.append(latch.take())
            InterleavingHarness(seed=seed).run(
                writer(e1), writer(e2), taker)
            leftovers = latch.take()
            observed = [x for x in taken + [leftovers] if x is not None]
            # each error surfaces AT MOST once (a take between the two
            # records legally yields both), at least one surfaces, and
            # nothing is duplicated — the exactly-once contract
            assert 1 <= len(observed) <= 2
            assert len(set(map(id, observed))) == len(observed)
            assert all(x in (e1, e2) for x in observed)
            assert latch.take() is None

    def test_delivered_clears_only_its_own_error(self):
        from deeplearning4j_tpu.data.dataset import _ErrorLatch
        latch = _ErrorLatch()
        kept, stale = RuntimeError("kept"), RuntimeError("stale")
        latch.record(kept)
        latch.delivered(stale)      # not the latched one: no-op
        assert latch.take() is kept
        assert latch.take() is None


@races
class TestAsyncIteratorErrorRace:
    """If the worker hit the error it must surface exactly once — via
    next() OR close(), never both, never twice — while close() races the
    worker. (A close() that stops the worker BEFORE it reached the
    failing next() legitimately surfaces nothing: there is no error.)"""

    def _failing_iter(self, n_good, err):
        from deeplearning4j_tpu.data.dataset import (DataSet,
                                                     ListDataSetIterator)

        class Failing(ListDataSetIterator):
            def __init__(self):
                x = np.zeros((n_good + 1, 2), np.float32)
                super().__init__(DataSet(x, x), batch_size=1)
                self._served = 0
                self.raised = False

            def next(self):
                if self._served >= n_good:
                    self.raised = True
                    raise err
                self._served += 1
                return super().next()
        return Failing()

    def test_exactly_once_error_under_stress(self):
        from deeplearning4j_tpu.data.dataset import AsyncDataSetIterator
        err = IOError("worker blew up")
        with preemptive_stress(seed=7) as rng:
            for trial in range(20):
                source = self._failing_iter(2, err)
                it = AsyncDataSetIterator(source, prefetch=1)
                surfaced = 0
                try:
                    pulls = rng.randint(0, 3)
                    for _ in range(pulls):
                        if not it.hasNext():
                            break
                        it.next()
                except IOError:
                    surfaced += 1
                time.sleep(rng.random() * 0.002)
                try:
                    it.close()
                except IOError:
                    surfaced += 1
                # idempotent double-close never re-raises
                it.close()
                # close() joins the worker, so `raised` is settled here:
                # an error that happened surfaces exactly once; a worker
                # stopped before the failing next() surfaces nothing
                want = 1 if source.raised else 0
                assert surfaced == want, \
                    f"trial {trial}: {surfaced} != {want}"


class _EchoModel:
    """Fake model for ModelServer: output == input (numpy round-trip)."""

    def output(self, x):
        return np.asarray(x)


@races
class TestServingStatsRace:
    """Regression for the ModelServer E201/E202 fixes: outcome counts,
    batch counter, and lifecycle flags stay consistent while many
    submitters race the serve thread."""

    def test_counts_and_batches_consistent_under_stress(self):
        from deeplearning4j_tpu.serving import ModelServer
        n_threads, per_thread = 4, 25
        with preemptive_stress(seed=3):
            server = ModelServer(_EchoModel(), batch_limit=8,
                                 max_queue=1024, coalesce_ms=0.5)
            server.warmup([(3,)])
            results = [0] * n_threads

            def client(i):
                ok = 0
                for _ in range(per_thread):
                    try:
                        server.submit(np.ones((1, 3), np.float32)).get(10.0)
                        ok += 1
                    except Exception:
                        pass
                results[i] = ok
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60.0)
            server.drain()
            stats = server.stats()
            server.close()
        # every submitted request has exactly one terminal outcome
        assert sum(stats["counts"].values()) == n_threads * per_thread
        assert stats["counts"].get("completed", 0) == sum(results)
        assert stats["batches"] >= 1
        assert stats["recompiles_after_warmup"] == 0

    def test_warmup_flags_visible_after_racing_submits(self):
        from deeplearning4j_tpu.serving import ModelServer
        server = ModelServer(_EchoModel(), batch_limit=4, coalesce_ms=0.5)
        server.warmup([(2,)])
        out = server.output(np.ones((1, 2), np.float32), timeout=10.0)
        assert out.shape == (1, 2)
        server.close()


@races
class TestAsyncWriterErrorRace:
    """Regression for the _AsyncWriter.error fix: a failure recorded by
    the writer thread is taken exactly once by the fit thread."""

    def test_take_error_exactly_once(self):
        from deeplearning4j_tpu.train.resilience import _AsyncWriter

        class Boom:
            def _write(self, *a, **kw):
                raise OSError("disk gone")
        w = _AsyncWriter(Boom(), depth=2)
        try:
            w.submit((None, "s", None, None, None))
            w.flush()
            takes = [w.take_error() for _ in range(3)]
            errs = [e for e in takes if e is not None]
            assert len(errs) == 1 and isinstance(errs[0], OSError)
        finally:
            w.close()

    def test_first_of_racing_failures_wins(self):
        from deeplearning4j_tpu.train.resilience import _AsyncWriter

        class Boom:
            def __init__(self):
                self.n = 0

            def _write(self, *a, **kw):
                self.n += 1
                raise OSError(f"failure {self.n}")
        w = _AsyncWriter(Boom(), depth=2)
        try:
            for _ in range(3):
                w.submit((None, "s", None, None, None))
            w.flush()
            err = w.take_error()
            assert str(err) == "failure 1"       # FIRST failure is kept
        finally:
            w.close()


@races
class TestStatsStorageRace:
    """Regression for the ui/stats hardening: concurrent put/get/
    register never lose a record or crash an iterator."""

    def test_concurrent_puts_and_reads(self):
        from deeplearning4j_tpu.ui.stats import InMemoryStatsStorage
        storage = InMemoryStatsStorage()
        seen = []
        n_writers, per_writer = 3, 30

        def writer(wid):
            for i in range(per_writer):
                storage.putUpdate({"session_id": "s", "iteration": i,
                                   "worker_id": str(wid)})

        def reader():
            for _ in range(50):
                storage.listSessionIDs()
                storage.getAllUpdates("s")
                storage.getStaticInfo("s")
                storage.registerStatsStorageListener(seen.append)
        with preemptive_stress(seed=11):
            threads = [threading.Thread(target=writer, args=(w,))
                       for w in range(n_writers)] \
                + [threading.Thread(target=reader)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30.0)
        assert len(storage.getAllUpdates("s")) == n_writers * per_writer

    def test_uiserver_stop_joins_thread(self):
        from deeplearning4j_tpu.ui.server import UIServer
        ui = UIServer(port=0)
        ui.attach_serving(None)         # starts the HTTP thread
        thread = ui._thread
        assert thread is not None and thread.is_alive()
        ui.stop()
        assert not thread.is_alive()    # W212 fix: stop() joins


@races
class TestInstrumentedLocks:
    def setup_method(self):
        prof.set_profiling_mode(None)
        prof.disable_lock_order_witness()

    teardown_method = setup_method

    def _hist_count(self, name, label):
        m = prof.get_registry().get(name)
        child = m.children().get((label,))
        return child.count if child is not None else 0

    def test_wait_hold_contention_recorded_under_profiling(self):
        prof.set_profiling_mode(prof.ProfilingMode.BASIC)
        lock = prof.InstrumentedLock("test:contended")
        before_hold = self._hist_count("dl4j_lock_hold_seconds",
                                       "test:contended")
        entered = threading.Event()

        def holder():
            with lock:
                entered.set()
                time.sleep(0.05)
        t = threading.Thread(target=holder)
        t.start()
        entered.wait(5.0)
        with lock:                      # must block on the holder
            pass
        t.join(5.0)
        assert self._hist_count("dl4j_lock_hold_seconds",
                                "test:contended") == before_hold + 2
        assert self._hist_count("dl4j_lock_wait_seconds",
                                "test:contended") >= 1
        cont = prof.get_registry().get("dl4j_lock_contention_total")
        assert cont.children()[("test:contended",)].value >= 1

    def test_off_mode_records_nothing(self):
        lock = prof.InstrumentedLock("test:off")
        with lock:
            pass
        assert self._hist_count("dl4j_lock_hold_seconds", "test:off") == 0

    def test_rlock_locked_probe(self):
        # _thread.RLock.locked() is missing on older CPython — the
        # drop-in surface must still answer, without mutating state
        rl = prof.InstrumentedRLock("test:rlock")
        assert rl.locked() is False
        with rl:
            assert rl.locked() is True      # owned by us
            seen = []
            t = threading.Thread(target=lambda: seen.append(rl.locked()))
            t.start()
            t.join(5.0)
            assert seen == [True]           # held by another thread
        assert rl.locked() is False

    def test_rlock_reentry_and_condition_wait(self):
        prof.set_profiling_mode(prof.ProfilingMode.BASIC)
        cond = prof.InstrumentedCondition("test:cond")
        got = []

        def waiter():
            with cond:
                while not got:
                    if not cond.wait(5.0):
                        return
                got.append("woke")
        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.02)
        with cond:
            with cond:                  # re-entrant
                got.append("sent")
                cond.notify_all()
        t.join(5.0)
        assert got == ["sent", "woke"]

    def test_witness_raises_on_inversion_and_releases(self):
        prof.enable_lock_order_witness()
        a = prof.InstrumentedLock("test:A")
        b = prof.InstrumentedLock("test:B")
        with a:
            with b:
                pass
        with pytest.raises(prof.LockOrderInversionError):
            with b:
                with a:
                    pass
        # the failed acquire must not strand either lock
        assert not a.locked() and not b.locked()
        assert ("test:A", "test:B") in prof.lock_order_edges()

    def test_witness_disable_while_held_leaves_no_stale_entry(self):
        # acquire with the witness ON, release with it OFF: the held
        # stack must still pop, or the stale name fakes an inversion
        # against the next session's single consistent order
        a = prof.InstrumentedLock("test:stale-A")
        b = prof.InstrumentedLock("test:stale-B")
        prof.enable_lock_order_witness()
        a.acquire()
        prof.disable_lock_order_witness()
        a.release()
        prof.enable_lock_order_witness()
        with b:
            with a:                     # only-ever order b->a: clean
                pass

    def test_witness_warn_mode_and_consistent_order_clean(self):
        prof.enable_lock_order_witness(raise_on_inversion=False)
        a = prof.InstrumentedLock("test:C")
        b = prof.InstrumentedLock("test:D")
        for _ in range(3):              # one order only: no warning
            with a:
                with b:
                    pass
        with pytest.warns(RuntimeWarning, match="lock-order inversion"):
            with b:
                with a:
                    pass

    def test_serving_condition_is_instrumented(self):
        from deeplearning4j_tpu.serving.server import ModelServer
        server = ModelServer(_EchoModel(), batch_limit=4)
        try:
            assert isinstance(server._cond, prof.InstrumentedCondition)
            assert isinstance(server.breaker._lock, prof.InstrumentedLock)
        finally:
            server.close()


class TestInstrumentedQueue:
    """PR-10 adoption: queue.Queue drop-in with instrumented internals
    (the DevicePrefetcher/AsyncDataSetIterator hot-path queues)."""

    def setup_method(self):
        prof.set_profiling_mode(None)
        prof.disable_lock_order_witness()

    teardown_method = setup_method

    def _hold_count(self, label):
        m = prof.get_registry().get("dl4j_lock_hold_seconds")
        child = m.children().get((label,))
        return child.count if child is not None else 0

    def test_drop_in_queue_semantics(self):
        import queue
        q = prof.InstrumentedQueue(maxsize=2, name="test:q")
        q.put(1)
        q.put(2)
        with pytest.raises(queue.Full):
            q.put_nowait(3)
        assert q.get() == 1 and q.get() == 2
        with pytest.raises(queue.Empty):
            q.get_nowait()
        assert q.qsize() == 0 and q.empty()

    def test_blocking_handoff_across_threads(self):
        q = prof.InstrumentedQueue(maxsize=1, name="test:q_handoff")
        got = []

        def consumer():
            for _ in range(20):
                got.append(q.get())
        t = threading.Thread(target=consumer)
        t.start()
        for i in range(20):
            q.put(i)
        t.join(10.0)
        assert got == list(range(20))

    def test_records_under_profiling_free_when_off(self):
        before_off = self._hold_count("test:q_metrics")
        q = prof.InstrumentedQueue(name="test:q_metrics")
        q.put(1)
        q.get()
        assert self._hold_count("test:q_metrics") == before_off  # OFF: nothing
        prof.set_profiling_mode(prof.ProfilingMode.BASIC)
        q.put(2)
        q.get()
        assert self._hold_count("test:q_metrics") > before_off

    @races
    def test_prefetcher_queue_instrumented_end_to_end(self):
        """The real DevicePrefetcher runs on an InstrumentedQueue and
        still delivers every staged batch under preemptive stress."""
        from deeplearning4j_tpu.data.dataset import (DataSet,
                                                     DevicePrefetcher)
        prof.set_profiling_mode(prof.ProfilingMode.BASIC)
        rng = np.random.RandomState(0)
        batches = [DataSet(rng.randn(4, 3).astype(np.float32),
                           np.eye(2, dtype=np.float32)[rng.randint(0, 2, 4)])
                   for _ in range(16)]
        with preemptive_stress(seed=5):
            with DevicePrefetcher(iter(batches), prefetch=2) as pf:
                seen = sum(1 for _ in pf)
        assert seen == 16
        assert isinstance(pf._queue, prof.InstrumentedQueue)
        assert self._hold_count("prefetch_queue") > 0

    def test_registry_lock_is_instrumented(self):
        """PR-8 carried follow-up pin: the metrics registry's hot-path
        get-or-create lock reports into dl4j_lock_* when profiling."""
        reg = prof.get_registry()
        assert isinstance(reg._lock, prof.InstrumentedLock)
        assert reg._lock.name == "metrics_registry"
        prof.set_profiling_mode(prof.ProfilingMode.BASIC)
        before = self._hold_count("metrics_registry")
        reg.gauge("dl4j_test_registry_lock_probe", "probe")
        assert self._hold_count("metrics_registry") > before
