"""Shape functions + op-level validation (ref: DeclarableOp shape fns /
calculateOutputShape; SURVEY.md §2.1, VERDICT r3 #4)."""

import numpy as np
import pytest

pytestmark = pytest.mark.quick
import jax.numpy as jnp

from deeplearning4j_tpu.ops.shapes import OpShapeError, infer_shape


class TestShapeTable:
    def test_conv2d_shape(self):
        assert infer_shape("conv2d", (2, 3, 32, 32), (16, 3, 3, 3),
                           pad=1) == (2, 16, 32, 32)
        assert infer_shape("conv2d", (2, 3, 32, 32), (16, 3, 3, 3),
                           stride=2, pad=1) == (2, 16, 16, 16)
        assert infer_shape("conv2d", (2, 32, 32, 3), (16, 3, 3, 3),
                           mode="same",
                           data_format="NHWC") == (2, 32, 32, 16)

    def test_conv2d_bad_rank_message(self):
        with pytest.raises(OpShapeError,
                           match=r"Conv2D: expected NCHW \[N,C,H,W\], "
                                 r"got rank 3"):
            infer_shape("conv2d", (3, 32, 32), (16, 3, 3, 3))

    def test_conv2d_channel_mismatch_message(self):
        with pytest.raises(OpShapeError, match="4 channels but weights"):
            infer_shape("conv2d", (2, 4, 8, 8), (16, 3, 3, 3))

    def test_conv2d_real_call_raises(self):
        from deeplearning4j_tpu.ops import convolution as conv
        with pytest.raises(OpShapeError, match="got rank 3"):
            conv.conv2d(jnp.ones((3, 8, 8)), jnp.ones((4, 3, 3, 3)))

    def test_conv_output_collapse_rejected(self):
        with pytest.raises(ValueError, match="cannot be applied"):
            infer_shape("conv2d", (1, 3, 2, 2), (8, 3, 5, 5))

    def test_conv1d_conv3d(self):
        assert infer_shape("conv1d", (2, 3, 10), (8, 3, 3),
                           pad=1) == (2, 8, 10)
        assert infer_shape("conv3d", (1, 2, 8, 8, 8), (4, 2, 3, 3, 3),
                           pad=1) == (1, 4, 8, 8, 8)
        with pytest.raises(OpShapeError, match="Conv3D"):
            infer_shape("conv3d", (1, 2, 8, 8), (4, 2, 3, 3, 3))

    def test_pools(self):
        assert infer_shape("maxpool2d", (2, 8, 16, 16),
                           kernel=2) == (2, 8, 8, 8)
        with pytest.raises(OpShapeError, match="MaxPool2D"):
            infer_shape("maxpool2d", (8, 16, 16), kernel=2)

    def test_deconv2d(self):
        assert infer_shape("deconv2d", (1, 8, 8, 8), (4, 8, 2, 2),
                           stride=2) == (1, 4, 16, 16)

    def test_matmul(self):
        assert infer_shape("matmul", (4, 5), (5, 7)) == (4, 7)
        assert infer_shape("matmul", (2, 4, 5), (2, 5, 7)) == (2, 4, 7)
        assert infer_shape("matmul", (4, 5), (7, 5),
                           transpose_b=True) == (4, 7)
        with pytest.raises(OpShapeError, match="inner dims mismatch"):
            infer_shape("matmul", (4, 5), (6, 7))

    def test_rnn(self):
        out, (h, c) = infer_shape("lstmLayer", (10, 2, 8), (8, 16), (4, 16),
                                  (16,))
        assert out == (10, 2, 4) and h == (2, 4) and c == (2, 4)
        with pytest.raises(OpShapeError, match="LstmLayer"):
            infer_shape("lstmLayer", (10, 8), (8, 16), (4, 16), (16,))
        out, h = infer_shape("gru", (5, 3, 6), (6, 12), (4, 12), (12,), (12,))
        assert out == (5, 3, 4)

    def test_linalg(self):
        assert infer_shape("cholesky", (4, 4)) == (4, 4)
        with pytest.raises(OpShapeError, match="square"):
            infer_shape("cholesky", (4, 5))
        assert infer_shape("solve", (4, 4), (4, 2)) == (4, 2)
        u, s, v = infer_shape("svd", (6, 4))
        assert u == (6, 4) and s == (4,) and v == (4, 4)

    def test_eval_shape_fallback(self):
        # ops outside the table answer through abstract interpretation
        assert infer_shape("softplus", (3, 4)) == (3, 4)
        assert infer_shape("reduce_sum", (3, 4), axis=1) == (3,)
        assert infer_shape("transpose", (2, 5)) == (5, 2)


class TestSameDiffSummary:
    def test_summary_prints_shapes_without_execution(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 6))
        w = sd.var("w", np.random.RandomState(0).randn(6, 4)
                   .astype(np.float32))
        y = x.mmul(w)
        z = y.relu().sum(1)
        s = sd.summary(batch_size=32)
        assert "(32, 6)" in s      # placeholder with batch substituted
        assert "(32, 4)" in s      # matmul output
        assert "(32,)" in s        # reduction output
        shapes = sd.infer_shapes(batch_size=7)
        assert shapes[y.name] == (7, 4)
        assert shapes[z.name] == (7,)

    def test_summary_covers_rng_nodes(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(4, 8))
        d = sd.nn.dropout(x, 0.5)
        assert sd.infer_shapes()[d.name] == (4, 8)


class TestReviewRegressions:
    def test_grouped_conv1d_passes_shape_check(self):
        from deeplearning4j_tpu.ops import convolution as conv
        import jax.numpy as jnp
        out = conv.conv1d(jnp.ones((1, 4, 8)), jnp.ones((6, 2, 3)), groups=2)
        assert out.shape == (1, 6, 6)

    def test_summary_with_rankless_placeholder(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        x = sd.placeHolder("x")                 # no declared shape
        w = sd.var("w", np.random.randn(4, 2).astype(np.float32))
        y = x.mmul(w)
        s = sd.summary()                        # must not crash
        assert "None" in s                      # unknown shapes reported

    def test_lstm_layer_cell_clip_honors_mask(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.ops import registry as R
        rng = np.random.RandomState(0)
        T, N, C, H = 6, 2, 3, 4
        x = jnp.asarray(rng.randn(T, N, C).astype(np.float32))
        wi = jnp.asarray(rng.randn(C, 4 * H).astype(np.float32) * 0.4)
        wh = jnp.asarray(rng.randn(H, 4 * H).astype(np.float32) * 0.4)
        b = jnp.zeros((4 * H,), jnp.float32)
        mask = jnp.asarray(np.array([[1, 1], [1, 1], [1, 1],
                                     [0, 1], [0, 1], [0, 1]], np.float32))
        out, _ = R.get("lstmLayer")(x, wi, wh, b, mask_tn=mask, cell_clip=5.0)
        # masked steps (batch item 0, t>=3) must emit zeros
        assert float(jnp.sum(jnp.abs(out[3:, 0]))) == 0.0
        assert float(jnp.sum(jnp.abs(out[3:, 1]))) > 0.0

    def test_recurrent_attention_multihead(self):
        from deeplearning4j_tpu.nn.layers import RecurrentAttentionLayer
        import jax
        import jax.numpy as jnp
        layer = RecurrentAttentionLayer(nOut=6, nHeads=2, nIn=4,
                                        weightInit="xavier",
                                        activation="tanh")
        params, _ = layer.initialize(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.RandomState(0)
                        .randn(3, 4, 5).astype(np.float32))
        out, _ = layer.apply(params, {}, x, False, jax.random.PRNGKey(0))
        assert out.shape == (3, 6, 5)
        with pytest.raises(ValueError, match="not\\s+divisible"):
            bad = RecurrentAttentionLayer(nOut=6, nHeads=3, nIn=4,
                                          weightInit="xavier",
                                          activation="tanh")
            p, _ = bad.initialize(jax.random.PRNGKey(0))
            bad.apply(p, {}, x, False, jax.random.PRNGKey(0))
