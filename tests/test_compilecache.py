"""Persistent/AOT compilation cache + unified warmup (ISSUE 13).

Pins, per tier:

- DiskCompileCache: atomic write/read roundtrip, corruption QUARANTINE
  (never trusted, renamed aside), version-mismatch = ignored+rewritten,
  LRU eviction past max_entries.
- CachedDispatch: plain-jit passthrough when disabled, AOT warm()
  compiles without executing, in-process disk reuse across instances,
  graceful fallback when serialization breaks.
- THE cross-process pin: a second fresh process reports disk misses==0
  and ZERO cold compile seconds for the same (model, shapes, policy)
  across fit, resume (checkpoint-recorded batch signature), and
  serving bucket warmup.
- Key busting: a policy or mesh/sharding change maps to different
  entries (no false sharing).
- The existing zero-steady-state-recompile pins stay green with the
  persistent cache enabled (megastep, serving buckets, precision
  re-attach).
- Concurrent writers race safely (``-m races``).
- DL4J-W112: serving warmup without a (writable) persistent cache dir.
"""

import json
import os
import subprocess
import sys
import threading
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.analysis import get_churn_detector
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn import compilecache as cc
from deeplearning4j_tpu.nn.config import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.updaters import Adam
from deeplearning4j_tpu.serving.server import ModelServer


@pytest.fixture(autouse=True)
def _clean_cache_config():
    """Every test starts with the cache disabled and zeroed stats, and
    cannot leak its configuration into the rest of the suite."""
    cc.configure(None)
    cc.reset_stats()
    yield
    cc.reset_configuration()
    cc.reset_stats()


def _mlp_conf(seed=7, hidden=16):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(0.01))
            .weightInit("xavier").list()
            .layer(DenseLayer(nOut=hidden, activation="relu"))
            .layer(OutputLayer(nOut=3, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(8))
            .build())


def _graph_conf(seed=7):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(0.01))
            .graphBuilder()
            .addInputs("in")
            .setInputTypes(InputType.feedForward(8))
            .addLayer("fc", DenseLayer(nOut=16, activation="relu"), "in")
            .addLayer("out", OutputLayer(nOut=3, lossFunction="mcxent",
                                         activation="softmax"), "fc")
            .setOutputs("out")
            .build())


def _data(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return DataSet(rng.randn(n, 8).astype(np.float32),
                   np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)])


def _iterator(seed=0, n=48, batch=8):
    """Cursor-capable source (exact resume needs seek())."""
    from deeplearning4j_tpu.data.dataset import ListDataSetIterator
    return ListDataSetIterator(_data(n, seed), batch_size=batch)


# ------------------------------------------------------------- disk store
class TestDiskStore:
    def test_roundtrip(self, tmp_path):
        store = cc.DiskCompileCache(str(tmp_path))
        key = cc.content_key("t", b"program-bytes", ("part",))
        assert store.get(key) is None
        store.put(key, b"payload", scope="t")
        assert store.get(key) == b"payload"
        assert store.entry_count() == 1

    def test_corrupt_entry_quarantined(self, tmp_path):
        store = cc.DiskCompileCache(str(tmp_path))
        key = cc.content_key("t", b"p", ())
        path = store.put(key, b"payload")
        with open(path, "r+b") as f:          # flip payload bytes: the
            f.seek(-3, os.SEEK_END)           # header checksum must catch
            f.write(b"zzz")
        with pytest.warns(UserWarning, match="quarantined corrupt"):
            assert store.get(key) is None
        assert not os.path.exists(path)
        quarantined = [n for n in os.listdir(tmp_path)
                       if n.startswith("quarantine_")]
        assert len(quarantined) == 1
        # a rewrite restores the entry
        store.put(key, b"payload")
        assert store.get(key) == b"payload"

    def test_truncated_entry_quarantined(self, tmp_path):
        store = cc.DiskCompileCache(str(tmp_path))
        key = cc.content_key("t", b"p2", ())
        path = store.put(key, b"payload-bytes")
        with open(path, "wb") as f:
            f.write(b"DL4")                  # not even the magic survives
        with pytest.warns(UserWarning, match="quarantined"):
            assert store.get(key) is None

    def test_version_mismatch_ignored_and_rewritten(self, tmp_path):
        store = cc.DiskCompileCache(str(tmp_path))
        key = cc.content_key("t", b"p3", ())
        path = store.put(key, b"payload")
        # doctor the header to an older runtime: ignored, NOT quarantined
        with open(path, "rb") as f:
            f.readline()
            header = json.loads(f.readline().decode())
            payload = f.read()
        header["runtime"] = "jax=0.0.1;jaxlib=0.0.1;backend=cpu"
        with open(path, "wb") as f:
            f.write(b"DL4JCC1\n")
            f.write(json.dumps(header).encode() + b"\n")
            f.write(payload)
        assert store.get(key) is None
        assert os.path.exists(path)           # still there — and a fresh
        store.put(key, b"payload")            # put overwrites it in place
        assert store.get(key) == b"payload"

    def test_eviction_lru(self, tmp_path):
        store = cc.DiskCompileCache(str(tmp_path), max_entries=3)
        keys = [cc.content_key("t", f"p{i}".encode(), ()) for i in range(5)]
        for i, k in enumerate(keys):
            store.put(k, b"x")
            os.utime(store._path(k), (1000 + i, 1000 + i))
        store.put(keys[0], b"x")              # refresh + trigger evict
        assert store.entry_count() == 3

    def test_eviction_grace_window(self, tmp_path):
        """ISSUE 17 satellite: entries younger than the grace window are
        never evicted even over capacity — a concurrent multi-host
        writer may not have loaded its own fresh entry yet."""
        store = cc.DiskCompileCache(str(tmp_path), max_entries=2)
        keys = [cc.content_key("t", f"g{i}".encode(), ()) for i in range(4)]
        for k in keys:
            store.put(k, b"x")
        # every put triggered _evict, but all 4 entries are fresh
        assert store.entry_count() == 4
        for k in keys[:2]:                    # age the two oldest
            os.utime(store._path(k), (1000, 1000))
        store._evict()
        assert store.entry_count() == 2
        assert store.get(keys[3]) == b"x"     # fresh survivors intact
        assert store.get(keys[2]) == b"x"
        assert store.get(keys[0]) is None

    def test_eviction_survives_vanishing_entry(self, tmp_path, monkeypatch):
        """An entry vanishing between listdir and getmtime (another
        host's evictor won the race) is skipped — the sweep still
        removes the remaining cold excess instead of aborting."""
        store = cc.DiskCompileCache(str(tmp_path), max_entries=1)
        keys = [cc.content_key("t", f"v{i}".encode(), ()) for i in range(3)]
        for k in keys:                        # all fresh: grace-protected
            store.put(k, b"x")
        for i, k in enumerate(keys):          # now age them together
            os.utime(store._path(k), (1000 + i, 1000 + i))
        ghost = store._path(keys[1])
        real_getmtime = os.path.getmtime

        def getmtime(p):
            if p == ghost:
                raise OSError("vanished")
            return real_getmtime(p)
        monkeypatch.setattr(cc.os.path, "getmtime", getmtime)
        store._evict()                        # sees 2 entries, excess 1
        monkeypatch.undo()
        assert store.entry_count() == 2       # oldest visible one removed
        assert store.get(keys[0]) is None

    def test_eviction_survives_concurrent_remove(self, tmp_path,
                                                 monkeypatch):
        """os.remove losing a race with another evictor (entry already
        gone) still counts toward the excess and the sweep continues."""
        store = cc.DiskCompileCache(str(tmp_path), max_entries=1)
        keys = [cc.content_key("t", f"r{i}".encode(), ()) for i in range(3)]
        for k in keys:                        # all fresh: grace-protected
            store.put(k, b"x")
        for i, k in enumerate(keys):          # now age them together
            os.utime(store._path(k), (1000 + i, 1000 + i))
        real_remove = os.remove
        raced = []

        def remove(p):
            real_remove(p)                    # the "other evictor" won...
            if not raced:
                raced.append(p)
                raise OSError("already gone")  # ...so ours sees ENOENT
        monkeypatch.setattr(cc.os, "remove", remove)
        store._evict()
        monkeypatch.undo()
        assert raced                          # the race actually happened
        assert store.entry_count() == 1
        assert store.get(keys[2]) == b"x"

    def test_concurrent_put_same_key_atomic(self, tmp_path):
        store = cc.DiskCompileCache(str(tmp_path))
        key = cc.content_key("t", b"race", ())
        payload = b"P" * 4096
        errors = []
        barrier = threading.Barrier(4)

        def writer():
            try:
                barrier.wait()
                for _ in range(20):
                    store.put(key, payload)
                    got = store.get(key)
                    assert got == payload
            except BaseException as e:          # noqa: B017
                errors.append(e)
        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert store.get(key) == payload

    def test_cache_dir_status(self, tmp_path):
        assert cc.cache_dir_status() == (None, False)
        cc.configure(str(tmp_path))
        d, writable = cc.cache_dir_status()
        assert d == str(tmp_path) and writable
        # unwritable: a path whose "parent" is a regular file (chmod
        # tricks don't work under root, which CI may run as)
        blocker = tmp_path / "blocker"
        blocker.write_text("x")
        cc.configure(str(blocker / "sub"))
        d, writable = cc.cache_dir_status()
        assert not writable

    def test_env_var_resolution(self, tmp_path, monkeypatch):
        cc.reset_configuration()
        monkeypatch.setenv(cc.ENV_DIR, str(tmp_path))
        assert cc.cache_dir() == str(tmp_path)
        cc.configure(None)                    # explicit disable wins
        assert cc.cache_dir() is None


# -------------------------------------------------------- cached dispatch
class TestCachedDispatch:
    def test_passthrough_when_disabled(self):
        calls = []

        def f(x):
            calls.append(1)
            return x * 2
        d = cc.cached_dispatch(f, "test:pt")
        out = d(jnp.ones((4,)))
        assert float(out[0]) == 2.0
        assert d.warmed_signatures() == 0     # plain jit path, no AOT

    def test_warm_compiles_without_executing(self, tmp_path):
        cc.configure(str(tmp_path))
        executed = []

        def f(x):
            executed.append(1)                # traced once, run never
            return x + 1
        d = cc.cached_dispatch(f, "test:warm")
        d.warm(jnp.zeros((4,)))
        assert d.warmed_signatures() == 1
        stats = cc.cache_stats()
        assert stats["compile_seconds"]["cold_compiles"] == 1
        assert stats["disk"]["entries"] == 1
        # the call now hits the memory tier
        cc.reset_stats()
        assert float(d(jnp.ones((4,)))[0]) == 2.0
        assert cc.cache_stats()["memory"]["hits"] == 1

    def test_disk_reuse_across_instances(self, tmp_path):
        cc.configure(str(tmp_path))

        def f(x):
            return jnp.dot(x, x.T)
        cc.cached_dispatch(f, "test:reuse").warm(jnp.zeros((8, 8)))
        cc.reset_stats()
        d2 = cc.cached_dispatch(f, "test:reuse")
        d2.warm(jnp.zeros((8, 8)))
        s = cc.cache_stats()
        assert s["disk"]["hits"] == 1 and s["disk"]["misses"] == 0
        assert s["compile_seconds"]["cold_compiles"] == 0
        assert s["compile_seconds"]["warm_loads"] == 1
        out = d2(jnp.full((8, 8), 2.0))
        assert float(np.asarray(out)[0, 0]) == pytest.approx(32.0)

    def test_key_parts_bust(self, tmp_path):
        cc.configure(str(tmp_path))

        def f(x):
            return x * 3
        cc.cached_dispatch(f, "test:kp", key_parts=("a",)).warm(
            jnp.zeros((2,)))
        cc.reset_stats()
        cc.cached_dispatch(f, "test:kp", key_parts=("b",)).warm(
            jnp.zeros((2,)))
        s = cc.cache_stats()                  # different key part: a miss
        assert s["disk"]["misses"] == 1 and s["disk"]["hits"] == 0

    def test_serialize_failure_falls_back(self, tmp_path, monkeypatch):
        cc.configure(str(tmp_path))

        def boom(exe):
            raise RuntimeError("injected serialize failure")
        monkeypatch.setattr(cc, "_serialize_executable", boom)

        def f(x):
            return x - 1
        d = cc.cached_dispatch(f, "test:fb")
        with pytest.warns(UserWarning, match="persistent-cache write"):
            out = d(jnp.ones((2,)))
        assert float(out[0]) == 0.0           # dispatch survived
        assert cc.cache_stats()["disk"]["entries"] == 0

    def test_sharding_in_signature(self, tmp_path):
        from deeplearning4j_tpu.parallel.mesh import DeviceMesh
        if len(jax.devices()) < 2:
            pytest.skip("needs multi-device")
        cc.configure(str(tmp_path))

        def f(x):
            return x * 2
        d = cc.cached_dispatch(f, "test:shard")
        mesh = DeviceMesh.data_parallel()
        host = jnp.zeros((8, 4))
        with mesh:
            sharded = jax.device_put(host, mesh.batch_sharding(2))
            d.warm(sharded)
        d.warm(host)
        # two placements, two programs — a mesh change can never reuse
        # the single-device executable
        assert d.warmed_signatures() == 2


# ------------------------------------------------------------ model paths
class TestModelIntegration:
    def test_fit_bit_exact_with_cache(self, tmp_path):
        ds = _data()
        base = MultiLayerNetwork(_mlp_conf()).init()
        base.fit(ds, epochs=3)
        cc.configure(str(tmp_path))
        cached = MultiLayerNetwork(_mlp_conf()).init()
        cached.fit(ds, epochs=3)
        assert np.array_equal(np.asarray(base.params()),
                              np.asarray(cached.params()))
        assert cc.cache_stats()["disk"]["entries"] >= 1

    def test_fit_from_disk_bit_exact(self, tmp_path):
        """An executable DESERIALIZED from the store trains bit-exactly
        like a freshly compiled one."""
        ds = _data()
        cc.configure(str(tmp_path))
        a = MultiLayerNetwork(_mlp_conf()).init()
        a.fit(ds, epochs=2)                   # populates the store
        cc.reset_stats()
        b = MultiLayerNetwork(_mlp_conf()).init()
        b.fit(ds, epochs=2)                   # deserializes
        s = cc.cache_stats()
        assert s["disk"]["hits"] >= 1
        assert s["compile_seconds"]["cold_compiles"] == 0
        assert np.array_equal(np.asarray(a.params()), np.asarray(b.params()))

    def test_megastep_with_cache_bit_exact(self, tmp_path):
        batches = [_data(8, seed=i) for i in range(4)]
        base = MultiLayerNetwork(_mlp_conf()).init()
        base.fit(list(batches), epochs=1, steps_per_dispatch=2)
        cc.configure(str(tmp_path))
        cached = MultiLayerNetwork(_mlp_conf()).init()
        cached.fit(list(batches), epochs=1, steps_per_dispatch=2)
        assert np.array_equal(np.asarray(base.params()),
                              np.asarray(cached.params()))

    def test_graph_fit_with_cache(self, tmp_path):
        ds = _data()
        base = ComputationGraph(_graph_conf()).init()
        base.fit(ds, epochs=2)
        cc.configure(str(tmp_path))
        cached = ComputationGraph(_graph_conf()).init()
        cached.fit(ds, epochs=2)
        lb = [np.asarray(v) for v in jax.tree_util.tree_leaves(base._params)]
        lc = [np.asarray(v)
              for v in jax.tree_util.tree_leaves(cached._params)]
        assert all(np.array_equal(x, y) for x, y in zip(lb, lc))
        cc.reset_stats()
        g2 = ComputationGraph(_graph_conf()).init()
        g2.fit(ds, epochs=1)
        assert cc.cache_stats()["disk"]["hits"] >= 1

    def test_policy_change_busts_key(self, tmp_path):
        """Key busting: a different PrecisionPolicy must not reuse the
        fp32 executable (and vice versa)."""
        ds = _data()
        cc.configure(str(tmp_path))
        MultiLayerNetwork(_mlp_conf()).init().fit(ds, epochs=1)
        cc.reset_stats()
        MultiLayerNetwork(_mlp_conf()).init().fit(ds, epochs=1,
                                                  precision="bf16")
        s = cc.cache_stats()
        assert s["disk"]["misses"] >= 1       # bf16 = new program
        cc.reset_stats()
        MultiLayerNetwork(_mlp_conf()).init().fit(ds, epochs=1,
                                                  precision="bf16")
        s = cc.cache_stats()                  # second bf16 fit = disk hit
        assert s["disk"]["misses"] == 0 and s["disk"]["hits"] >= 1

    def test_zero_steady_state_recompiles_with_cache(self, tmp_path):
        """The churn-detector pin with the persistent cache enabled:
        20 steps of steady-state fit = ONE signature at the fit site."""
        cc.configure(str(tmp_path))
        det = get_churn_detector()
        net = MultiLayerNetwork(_mlp_conf()).init()
        before = det.signature_count("MultiLayerNetwork.fit", owner=net)
        for _ in range(20):
            net.fit(_data(), epochs=1)
        assert det.signature_count("MultiLayerNetwork.fit",
                                   owner=net) - before == 1

    def test_warmup_api_forward_and_train(self, tmp_path):
        cc.configure(str(tmp_path))
        net = MultiLayerNetwork(_mlp_conf()).init()
        cc.warmup(net, [((16, 8), (16, 3)), (16, 8)])
        s = cc.cache_stats()
        assert s["compile_seconds"]["cold_compiles"] == 2
        p_before = np.asarray(net.params())
        cc.reset_stats()
        net.fit(_data(), epochs=1)            # no compile at dispatch
        net.output(np.zeros((16, 8), np.float32))
        s = cc.cache_stats()
        assert s["compile_seconds"]["cold_compiles"] == 0
        assert s["memory"]["hits"] >= 2
        # warmup itself never touched state
        net2 = MultiLayerNetwork(_mlp_conf()).init()
        assert np.array_equal(p_before, np.asarray(net2.params()))

    def test_warmup_megastep(self, tmp_path):
        cc.configure(str(tmp_path))
        net = MultiLayerNetwork(_mlp_conf()).init()
        cc.warmup(net, [((8, 8), (8, 3))], steps_per_dispatch=2)
        cc.reset_stats()
        net.fit([_data(8, seed=i) for i in range(2)], epochs=1,
                steps_per_dispatch=2)
        assert cc.cache_stats()["compile_seconds"]["cold_compiles"] == 0

    def test_warmup_graph(self, tmp_path):
        cc.configure(str(tmp_path))
        g = ComputationGraph(_graph_conf()).init()
        cc.warmup(g, [((16, 8), (16, 3)), (16, 8)])
        cc.reset_stats()
        g.fit(_data(), epochs=1)
        g.output(np.zeros((16, 8), np.float32))
        assert cc.cache_stats()["compile_seconds"]["cold_compiles"] == 0

    def test_warmup_bad_spec_rejected(self):
        net = MultiLayerNetwork(_mlp_conf()).init()
        with pytest.raises(ValueError, match="warmup shape spec"):
            cc.warmup(net, [((1, 2), (3, 4), (5, 6))])

    def test_warmup_delegates_to_server(self, tmp_path):
        cc.configure(str(tmp_path))
        net = MultiLayerNetwork(_mlp_conf()).init()
        sv = ModelServer(net, batch_limit=8, name="cc-deleg")
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                cc.warmup(sv, [(8,)])
            assert sv._warmed and sv.recompiles_after_warmup() == 0
        finally:
            sv.close()


# ---------------------------------------------------------------- serving
class TestServingCache:
    def test_serving_warmup_zero_recompiles_with_cache(self, tmp_path):
        cc.configure(str(tmp_path))
        net = MultiLayerNetwork(_mlp_conf()).init()
        sv = ModelServer(net, batch_limit=8, name="cc-srv1")
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                sv.warmup([(8,)])
            out = sv.output(np.random.RandomState(0)
                            .randn(4, 8).astype(np.float32))
            assert out.shape == (4, 3)
            assert sv.recompiles_after_warmup() == 0
        finally:
            sv.close()

    def test_second_server_warmup_hits_disk(self, tmp_path):
        """The registry hot-swap staging scenario in miniature: warming
        a NEW server over a previously-seen (model, bucket, mesh)
        performs zero cold compiles."""
        cc.configure(str(tmp_path))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            sv1 = ModelServer(MultiLayerNetwork(_mlp_conf()).init(),
                              batch_limit=8, name="cc-srv2")
            sv1.warmup([(8,)])
            sv1.close()
            cc.reset_stats()
            sv2 = ModelServer(MultiLayerNetwork(_mlp_conf()).init(),
                              batch_limit=8, name="cc-srv3")
            sv2.warmup([(8,)])
        try:
            s = cc.cache_stats()
            assert s["compile_seconds"]["cold_compiles"] == 0
            assert s["disk"]["misses"] == 0 and s["disk"]["hits"] >= 1
            assert sv2.recompiles_after_warmup() == 0
        finally:
            sv2.close()

    def test_registry_load_staging_hits_disk(self, tmp_path):
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        cc.configure(str(tmp_path))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            reg = ModelRegistry(batch_limit=8)
            reg.load("m", MultiLayerNetwork(_mlp_conf()).init(),
                     shapes=[(8,)])
            cc.reset_stats()
            # v2 of the same architecture: AOT staging = pure disk reads
            reg.load("m", MultiLayerNetwork(_mlp_conf()).init())
            reg.roll("m")
        try:
            s = cc.cache_stats()
            assert s["compile_seconds"]["cold_compiles"] == 0
            assert s["disk"]["misses"] == 0 and s["disk"]["hits"] >= 1
        finally:
            reg.close()


# ----------------------------------------------------------------- resume
class TestResumeWarmup:
    def test_checkpoint_records_batch_signature(self, tmp_path):
        from deeplearning4j_tpu.train.resilience import CheckpointConfig
        net = MultiLayerNetwork(_mlp_conf()).init()
        ck = str(tmp_path / "ck")
        net.fit([_data(), _data(16, 1)], epochs=1,
                checkpoint=CheckpointConfig(ck, every_steps=1))
        cps = sorted(d for d in os.listdir(ck) if d.startswith("ckpt_"))
        with open(os.path.join(ck, cps[-1], "extra.json")) as f:
            extra = json.load(f)
        sig = extra["extra"]["resilience"]["batch_signature"]
        assert sig["features"] == [[16, 8], "float32"]
        assert sig["labels"] == [[16, 3], "float32"]

    def test_resume_warms_from_recorded_signature(self, tmp_path):
        from deeplearning4j_tpu.train.resilience import CheckpointConfig
        cc.configure(str(tmp_path / "cache"))
        ck = str(tmp_path / "ck")
        a = MultiLayerNetwork(_mlp_conf()).init()
        a.fit([_data(), _data(16, 1)], epochs=1,
              checkpoint=CheckpointConfig(ck, every_steps=1))
        # a "fresh process" stand-in: new model object, resume=True
        cc.reset_stats()
        b = MultiLayerNetwork(_mlp_conf()).init()
        b.fit([_data(), _data(16, 1)], epochs=2,
              checkpoint=CheckpointConfig(ck, resume=True))
        s = cc.cache_stats()
        assert s["compile_seconds"]["cold_compiles"] == 0
        assert s["disk"]["hits"] >= 1 and s["disk"]["misses"] == 0

    def test_resume_warm_noop_without_cache(self, tmp_path):
        """No cache dir configured -> warm_after_resume is a no-op and
        resumed fits behave exactly as before (and stay bit-exact)."""
        from deeplearning4j_tpu.train.resilience import CheckpointConfig
        from deeplearning4j_tpu.faults import FaultPlan
        ck = str(tmp_path / "ck")
        full = MultiLayerNetwork(_mlp_conf()).init()
        full.fit(_iterator(), epochs=1)
        part = MultiLayerNetwork(_mlp_conf()).init()
        part.fit(_iterator(), epochs=1,
                 checkpoint=CheckpointConfig(ck, every_steps=1),
                 faults=FaultPlan(preempt_at_step=2))
        resumed = MultiLayerNetwork(_mlp_conf()).init()
        resumed.fit(_iterator(), epochs=1,
                    checkpoint=CheckpointConfig(ck, resume=True))
        assert np.array_equal(np.asarray(full.params()),
                              np.asarray(resumed.params()))


# ---------------------------------------------------------------- elastic
class TestElasticWarm:
    def test_survivor_mesh_warm_populates_cache(self, tmp_path):
        """The shrink path's warm seam: given a checkpoint-recorded
        batch signature, the survivor-mesh megastep is AOT-compiled
        (padded + sharded like the dispatch loop stages it) without
        touching model state, and a repeat warm is a disk hit."""
        import types
        from deeplearning4j_tpu.parallel.elastic import _warm_survivor_mesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        if len(jax.devices()) < 2:
            pytest.skip("needs multi-device")
        cc.configure(str(tmp_path))
        net = MultiLayerNetwork(_mlp_conf()).init()
        wrapper = ParallelWrapper(net)
        session = types.SimpleNamespace(_last_batch_sig={
            "features": [[16, 8], "float32"],
            "labels": [[16, 3], "float32"]})
        p_before = np.asarray(net.params())
        _warm_survivor_mesh(wrapper, net, session, wrapper.mesh, k=2)
        s = cc.cache_stats()
        assert s["compile_seconds"]["cold_compiles"] == 1
        assert np.array_equal(p_before, np.asarray(net.params()))
        # a later process/mesh-twin warms from disk
        cc.reset_stats()
        net2 = MultiLayerNetwork(_mlp_conf()).init()
        _warm_survivor_mesh(ParallelWrapper(net2), net2, session,
                            wrapper.mesh, k=2)
        s = cc.cache_stats()
        assert s["compile_seconds"]["cold_compiles"] == 0
        assert s["disk"]["hits"] == 1

    def test_survivor_warm_noop_without_cache(self):
        import types
        from deeplearning4j_tpu.parallel.elastic import _warm_survivor_mesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        net = MultiLayerNetwork(_mlp_conf()).init()
        wrapper = ParallelWrapper(net)
        session = types.SimpleNamespace(_last_batch_sig={
            "features": [[16, 8], "float32"],
            "labels": [[16, 3], "float32"]})
        _warm_survivor_mesh(wrapper, net, session, wrapper.mesh, k=1)
        assert net._megastep_cache == {} and net._train_step_cache == {}


# ---------------------------------------------------------- cross-process
_XPROC = r"""
import json, sys, warnings
warnings.simplefilter("ignore")
import numpy as np
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration, InputType
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn import compilecache as cc
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.train.updaters import Adam
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.serving.server import ModelServer

cc.configure(sys.argv[1])
conf = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(0.01))
        .weightInit("xavier").list()
        .layer(DenseLayer(nOut=16, activation="relu"))
        .layer(OutputLayer(nOut=3, lossFunction="mcxent",
                           activation="softmax"))
        .setInputType(InputType.feedForward(8)).build())
net = MultiLayerNetwork(conf).init()
rng = np.random.RandomState(0)
ds = DataSet(rng.randn(16, 8).astype(np.float32),
             np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)])
net.fit(ds, epochs=2)
sv = ModelServer(net, batch_limit=8, name="xproc")
sv.warmup([(8,)])
sv.close()
print("PARAMS0=%.9e" % float(np.asarray(net.params())[0]))
print(json.dumps(cc.cache_stats()))
"""


def _run_xproc(cache_dir):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    env.pop("DL4J_TPU_COMPILE_CACHE_DIR", None)
    proc = subprocess.run([sys.executable, "-c", _XPROC, cache_dir],
                          capture_output=True, text=True, timeout=600,
                          env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.strip().splitlines()
    return lines[-2], json.loads(lines[-1])


class TestCrossProcess:
    def test_second_process_zero_misses_and_no_cold_compiles(self, tmp_path):
        """THE acceptance pin: fit + serving warmup in a fresh process
        over a populated cache report disk misses==0 and materially
        lower compile seconds (zero cold compiles), bit-identical
        training included."""
        d = str(tmp_path)
        p1, s1 = _run_xproc(d)
        assert s1["disk"]["misses"] >= 1          # first process populated
        assert s1["compile_seconds"]["cold"] > 0
        p2, s2 = _run_xproc(d)
        assert s2["disk"]["misses"] == 0
        assert s2["disk"]["hits"] >= 2            # train step + forward
        assert s2["compile_seconds"]["cold"] == 0.0
        assert s2["compile_seconds"]["warm"] < s1["compile_seconds"]["cold"]
        assert p1 == p2                           # cached exe = same math


# ------------------------------------------------------------------ races
@pytest.mark.races
class TestConcurrentWriters:
    def test_many_threads_one_key(self, tmp_path):
        """N threads AOT-compile the same program into one store
        concurrently: no corruption, every call correct, exactly one
        final entry readable."""
        cc.configure(str(tmp_path))
        errors = []
        barrier = threading.Barrier(6)

        def work(i):
            try:
                def f(x):
                    return x * 2 + 1
                d = cc.cached_dispatch(f, "races:onekey")
                barrier.wait()
                out = d(jnp.full((4,), float(i)))
                assert float(out[0]) == 2.0 * i + 1
            except BaseException as e:              # noqa: B017
                errors.append(e)
        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        disk = cc.disk_cache()
        assert disk.entry_count() == 1
        # and the surviving entry is loadable
        cc.reset_stats()

        def f(x):
            return x * 2 + 1
        cc.cached_dispatch(f, "races:onekey").warm(jnp.zeros((4,)))
        assert cc.cache_stats()["disk"]["hits"] == 1


# ------------------------------------------------------------------- W112
class TestW112:
    def _server(self):
        return ModelServer(MultiLayerNetwork(_mlp_conf()).init(),
                           batch_limit=8, name="w112")

    def test_warmup_without_cache_warns_w112(self):
        sv = self._server()
        try:
            with pytest.warns(UserWarning, match="DL4J-W112"):
                sv.warmup([(8,)])
        finally:
            sv.close()

    def test_warmup_with_cache_no_w112(self, tmp_path):
        cc.configure(str(tmp_path))
        sv = self._server()
        try:
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                sv.warmup([(8,)])
            assert not any("W112" in str(w.message) for w in caught)
        finally:
            sv.close()

    def test_unwritable_dir_warns_w112(self, tmp_path):
        blocker = tmp_path / "blocker"          # file-as-parent: root-proof
        blocker.write_text("x")
        cc.configure(str(blocker / "cache"))
        sv = self._server()
        try:
            with pytest.warns(UserWarning, match="writable"):
                sv.warmup([(8,)])
        finally:
            sv.close()

    def test_static_validate_stays_silent(self):
        """A pure-static validate() (no warmup) must NOT fire W112 —
        the pre-existing clean-bill pins depend on it."""
        sv = self._server()
        try:
            assert "DL4J-W112" not in sv.validate().codes()
            assert "DL4J-W112" in sv.validate(check_cache=True).codes()
        finally:
            sv.close()

    def test_lint_compile_cache_direct(self, tmp_path):
        from deeplearning4j_tpu.analysis import lint_compile_cache
        diags = lint_compile_cache()
        assert diags and diags[0].code == "DL4J-W112"
        cc.configure(str(tmp_path))
        assert lint_compile_cache() == []

    def test_w112_suppressible(self):
        sv = self._server()
        try:
            report = sv.validate(check_cache=True)
            assert "DL4J-W112" in report.codes()
            report2 = report.apply_config(suppress=["DL4J-W112"])
            assert "DL4J-W112" not in report2.codes()
        finally:
            sv.close()

    def test_w112_documented(self):
        from deeplearning4j_tpu.analysis.diagnostics import DIAGNOSTIC_CODES
        assert "DL4J-W112" in DIAGNOSTIC_CODES


# ------------------------------------------------------- tracer streaming
class TestTraceStreaming:
    def test_stream_past_ring_horizon(self, tmp_path):
        from deeplearning4j_tpu.profiler.tracer import SpanTracer
        tr = SpanTracer(capacity=10)
        path = str(tmp_path / "trace.json")
        tr.stream_to(path)
        for i in range(50):
            tr.add_event(f"span{i}", float(i), 1.0)
        assert len(tr) == 10                  # ring kept only the tail
        out = tr.stop_stream()
        assert out == path
        with open(path) as f:
            doc = json.load(f)                # valid JSON array
        names = [e["name"] for e in doc if e.get("ph") == "X"]
        assert names[:1] == ["span0"] and len(names) == 50

    def test_stream_truncated_is_loadable_prefix(self, tmp_path):
        """A killed process's stream (no stop_stream) is a truncated
        JSON array whose events are still individually parseable."""
        from deeplearning4j_tpu.profiler.tracer import (SpanTracer,
                                                        _STREAM_FLUSH_EVERY)
        tr = SpanTracer(capacity=4)
        path = str(tmp_path / "t.json")
        tr.stream_to(path)
        for i in range(_STREAM_FLUSH_EVERY + 10):
            tr.add_event(f"s{i}", float(i), 1.0)
        with open(path) as f:                 # flushed prefix on disk
            body = f.read()
        assert body.startswith("[\n")
        first = body[2:].split(",\n")[0]
        assert json.loads(first)["name"] == "s0"
        tr.stop_stream()

    def test_stream_to_same_path_idempotent(self, tmp_path):
        from deeplearning4j_tpu.profiler.tracer import SpanTracer
        tr = SpanTracer()
        path = str(tmp_path / "t.json")
        tr.stream_to(path)
        tr.add_event("a", 0.0, 1.0)
        tr.stream_to(path)                    # no restart, no truncation
        tr.add_event("b", 1.0, 1.0)
        tr.stop_stream()
        with open(path) as f:
            doc = json.load(f)
        assert [e["name"] for e in doc if e.get("ph") == "X"] == ["a", "b"]

    def test_stream_via_global_tracer_spans(self, tmp_path):
        from deeplearning4j_tpu import profiler as prof
        tr = prof.get_tracer()
        path = str(tmp_path / "g.json")
        tr.stream_to(path)
        prof.enable_tracing()
        try:
            with prof.trace_span("test:streamed"):
                pass
        finally:
            prof.disable_tracing()
            tr.stop_stream()
        with open(path) as f:
            doc = json.load(f)
        assert any(e["name"] == "test:streamed" for e in doc)


# ------------------------------------------------- dynamic loss scaling
class TestDynamicLossScaling:
    def test_policy_coercion_and_signature(self):
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        p = PrecisionPolicy("fp16", loss_scale="dynamic")
        assert p.is_dynamic and p.numeric_loss_scale() == 2.0 ** 15
        assert p.loss_scale_init == 2.0 ** 15
        q = PrecisionPolicy.from_config(p.to_config())
        assert q == p and q.signature() == p.signature()
        # a different knob = a different signature (cache bust)
        r = PrecisionPolicy("fp16", loss_scale="dynamic",
                            growth_interval=10)
        assert r.signature() != p.signature()
        with pytest.raises(ValueError, match="only string value"):
            PrecisionPolicy("fp16", loss_scale="auto")

    def test_static_pins_unchanged(self):
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        p = PrecisionPolicy("fp16", loss_scale=2048.0)
        assert not p.is_dynamic and p.numeric_loss_scale() == 2048.0
        assert p.signature() == ("float16", "float32", 2048.0)

    def test_no_overflow_equals_static_bit_exact(self):
        """With no overflow and growth disabled, dynamic(init=S) ==
        static(S) bit-exactly — the automaton is pure bookkeeping until
        something overflows."""
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        ds = _data()
        dyn = MultiLayerNetwork(_mlp_conf()).init()
        dyn.fit(ds, epochs=3, precision=PrecisionPolicy(
            "fp16", loss_scale="dynamic", loss_scale_init=2.0 ** 10,
            growth_interval=10 ** 9))
        st = MultiLayerNetwork(_mlp_conf()).init()
        st.fit(ds, epochs=3, precision=PrecisionPolicy(
            "fp16", loss_scale=2.0 ** 10))
        assert np.array_equal(np.asarray(dyn.params()),
                              np.asarray(st.params()))
        assert dyn.current_loss_scale() == 2.0 ** 10

    def test_overflow_skips_update_and_backs_off(self):
        """An absurd init scale overflows the fp16 backward: the step's
        update is DROPPED (params unchanged) and the scale halves."""
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.setPrecisionPolicy(PrecisionPolicy(
            "fp16", loss_scale="dynamic", loss_scale_init=2.0 ** 31))
        before = np.asarray(net.params())
        net.fit(_data(), epochs=1)
        assert np.array_equal(before, np.asarray(net.params()))
        assert net.current_loss_scale() == 2.0 ** 30
        # ...and training still makes progress once the scale descends
        for _ in range(25):
            net.fit(_data(), epochs=1)
        assert not np.array_equal(before, np.asarray(net.params()))
        assert net.current_loss_scale() < 2.0 ** 31

    def test_growth_after_interval(self):
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.setPrecisionPolicy(PrecisionPolicy(
            "fp16", loss_scale="dynamic", loss_scale_init=4.0,
            growth_interval=2))
        for _ in range(4):
            net.fit(_data(), epochs=1)
        assert net.current_loss_scale() == 16.0     # 4 -> 8 -> 16

    def test_growth_capped_at_max(self):
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.setPrecisionPolicy(PrecisionPolicy(
            "fp16", loss_scale="dynamic", loss_scale_init=8.0,
            growth_interval=1, max_loss_scale=16.0))
        for _ in range(5):
            net.fit(_data(), epochs=1)
        assert net.current_loss_scale() == 16.0

    def test_megastep_dynamic_equals_single_steps(self):
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        batches = [_data(8, seed=i) for i in range(4)]
        pol = PrecisionPolicy("fp16", loss_scale="dynamic",
                              loss_scale_init=2.0 ** 10,
                              growth_interval=3)
        a = MultiLayerNetwork(_mlp_conf()).init()
        a.fit(list(batches), epochs=1, steps_per_dispatch=2, precision=pol)
        b = MultiLayerNetwork(_mlp_conf()).init()
        b.fit(list(batches), epochs=1, precision=pol)
        assert np.array_equal(np.asarray(a.params()), np.asarray(b.params()))
        assert a.current_loss_scale() == b.current_loss_scale()

    def test_graph_dynamic_scaling(self):
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        ds = _data()
        dyn = ComputationGraph(_graph_conf()).init()
        dyn.fit(ds, epochs=2, precision=PrecisionPolicy(
            "fp16", loss_scale="dynamic", loss_scale_init=2.0 ** 10,
            growth_interval=10 ** 9))
        st = ComputationGraph(_graph_conf()).init()
        st.fit(ds, epochs=2, precision=PrecisionPolicy(
            "fp16", loss_scale=2.0 ** 10))
        ld = [np.asarray(v) for v in jax.tree_util.tree_leaves(dyn._params)]
        ls = [np.asarray(v) for v in jax.tree_util.tree_leaves(st._params)]
        assert all(np.array_equal(x, y) for x, y in zip(ld, ls))

    def test_scale_carried_through_checkpoint_resume(self, tmp_path):
        """Resume restores the automaton mid-flight: interrupted + resumed
        == uninterrupted, scale state included (bit-exact)."""
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        from deeplearning4j_tpu.train.resilience import CheckpointConfig
        from deeplearning4j_tpu.faults import FaultPlan
        pol = PrecisionPolicy("fp16", loss_scale="dynamic",
                              loss_scale_init=4.0, growth_interval=2)
        full = MultiLayerNetwork(_mlp_conf()).init()
        full.fit(_iterator(), epochs=1, precision=pol)
        ck = str(tmp_path / "ck")
        part = MultiLayerNetwork(_mlp_conf()).init()
        part.fit(_iterator(), epochs=1, precision=pol,
                 checkpoint=CheckpointConfig(ck, every_steps=1),
                 faults=FaultPlan(preempt_at_step=3))
        assert part.current_loss_scale() > 4.0      # grew before preempt
        res = MultiLayerNetwork(_mlp_conf()).init()
        res.fit(_iterator(), epochs=1, precision=pol,
                checkpoint=CheckpointConfig(ck, resume=True))
        assert np.array_equal(np.asarray(full.params()),
                              np.asarray(res.params()))
        assert res.current_loss_scale() == full.current_loss_scale()

    def test_policy_reattach_keeps_programs(self, tmp_path):
        """Equal dynamic policy re-attach keeps the compiled step (zero
        recompiles); a changed one busts it — with the persistent cache
        enabled."""
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        cc.configure(str(tmp_path))
        net = MultiLayerNetwork(_mlp_conf()).init()
        pol = PrecisionPolicy("fp16", loss_scale="dynamic",
                              loss_scale_init=2.0 ** 10)
        net.fit(_data(), epochs=1, precision=pol)
        step = net._train_step_cache[(False, False)]
        net.setPrecisionPolicy(PrecisionPolicy(
            "fp16", loss_scale="dynamic", loss_scale_init=2.0 ** 10))
        assert net._train_step_cache[(False, False)] is step
        net.setPrecisionPolicy(PrecisionPolicy(
            "fp16", loss_scale="dynamic", loss_scale_init=2.0 ** 8))
        assert (False, False) not in net._train_step_cache

    def test_sanitizer_attribution_with_dynamic_policy(self):
        """NAN_PANIC provenance must survive a dynamic policy: the
        replay rolls the scale carry and attributes the poisoned batch
        instead of crashing on the extra step argument."""
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        from deeplearning4j_tpu.profiler.modes import (ProfilingMode,
                                                       set_profiling_mode)
        from deeplearning4j_tpu.profiler.sanitizer import \
            NonfiniteAttributionError
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.setPrecisionPolicy(PrecisionPolicy(
            "fp16", loss_scale="dynamic", loss_scale_init=2.0 ** 8))
        set_profiling_mode(ProfilingMode.NAN_PANIC)
        try:
            net.fit(_data(), epochs=1)        # clean dispatch first
            bad = _data(seed=1)
            bad.features[0, 0] = np.nan
            with pytest.raises(NonfiniteAttributionError, match="batch"):
                net.fit(bad, epochs=1)
        finally:
            set_profiling_mode(ProfilingMode.OFF)

    def test_w302_handles_dynamic(self):
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        from deeplearning4j_tpu.analysis.numerics import lint_numerics
        # dynamic on bf16 is still pointless -> W302; on fp16 it is the
        # recommended configuration -> silent, and E303 (missing scale)
        # must NOT fire
        conf = _mlp_conf()
        rep = lint_numerics(conf, policy=PrecisionPolicy(
            "bf16", loss_scale="dynamic"))
        assert "DL4J-W302" in [d.code for d in rep]
        rep = lint_numerics(conf, policy=PrecisionPolicy(
            "fp16", loss_scale="dynamic"))
        codes = [d.code for d in rep]
        assert "DL4J-E303" not in codes and "DL4J-W302" not in codes
        # a dynamic automaton whose INIT scale already overflows the
        # declared range is judged at that worst case: every run starts
        # by dropping updates until backoff converges -> E303
        rep = lint_numerics(conf, policy=PrecisionPolicy(
            "fp16", loss_scale="dynamic", loss_scale_init=2.0 ** 24),
            data_range="0..255")
        assert "DL4J-E303" in [d.code for d in rep]

    def test_cli_accepts_dynamic_policy(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        rc = main(["LeNet", "--policy",
                   "compute=fp16,loss_scale=dynamic,growth_interval=100",
                   "--warnings-ok"])
        assert rc == 0
        with pytest.raises(SystemExit):        # typo'd scale: clean usage
            main(["LeNet", "--policy", "compute=fp16,loss_scale=auto"])
        capsys.readouterr()
