"""Test configuration: force CPU backend with 8 virtual devices.

Reference parity: the reference runs one test suite against N backends
(platform-tests with nd4j-native vs nd4j-cuda — SURVEY.md §4). Here the
suite runs on the CPU backend with a virtual 8-device mesh so every
sharding/parallelism test exercises real SPMD partitioning without TPU
hardware; the same code paths run unchanged on a real TPU slice.
"""

import os

# Must be set before jax is imported anywhere.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DL4J_TPU_MATMUL_PRECISION", "float32")

import jax  # noqa: E402

# The environment's TPU bootstrap (sitecustomize) pins jax_platforms to the
# TPU plugin via jax.config, which trumps the env var — pin it back to CPU
# after import so the suite runs on the 8-device virtual mesh.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "quick: fast smoke tier covering every subsystem "
        "(`pytest -m quick`, target <120s — the CI gate)")
    config.addinivalue_line(
        "markers",
        "races: seeded thread-interleaving tests (`pytest -m races`) — "
        "the InterleavingHarness determinism pins, the instrumented-lock "
        "layer, and one regression per E201/E202 repo fix. Like chaos, "
        "deliberately a fast marker so tier-1's `-m 'not slow'` gate "
        "runs every race schedule")
    config.addinivalue_line(
        "markers",
        "multihost: real multi-OS-process coordination tests "
        "(`pytest -m multihost`) — 2 worker processes rendezvous over "
        "the socket/file CoordinationService (ISSUE 15 tier 3: barrier "
        "agreement, dead-peer detection) or a gloo-backed global mesh. "
        "DELIBERATELY fast (<30 s for the socket tests) and NOT marked "
        "slow, so tier-1's `-m 'not slow'` gate runs the real-process "
        "coordination paths on every run")
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection sweeps through the resilience and "
        "elastic layers (`pytest -m chaos`). DELIBERATELY a fast marker, "
        "not a slow one: tier-1 runs `-m 'not slow'`, so every chaos "
        "sweep — including the elastic device-loss/hung-dispatch sweeps — "
        "is part of the default gate")
    config.addinivalue_line(
        "markers",
        "slow: long-running acceptance runs EXCLUDED from tier-1's "
        "`-m 'not slow'` gate (e.g. the ISSUE-17 "
        "`tune resnet50 --budget 20` step-time-reduction pin)")


# ---------------------------------------------------- tier-1 budget report
# The tier-1 gate is `-m 'not slow'` under a 1500 s timeout (ROADMAP).
# This report keeps the headroom visible on every run: total non-slow
# wall time vs the ceiling (warn at 80%) plus the slowest 10 non-slow
# tests — the candidates to optimize or demote to `slow` BEFORE the
# ceiling is hit, not after CI starts flaking on timeout.
TIER1_CEILING_S = 1500.0
TIER1_WARN_FRAC = 0.8
_test_durations: dict = {}
_slow_nodeids: set = set()


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.get_closest_marker("slow") is not None:
            _slow_nodeids.add(item.nodeid)


def pytest_runtest_logreport(report):
    # sum setup+call+teardown per nodeid
    _test_durations[report.nodeid] = (
        _test_durations.get(report.nodeid, 0.0) + report.duration)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    non_slow = {nid: d for nid, d in _test_durations.items()
                if nid not in _slow_nodeids}
    if not non_slow:
        return
    total = sum(non_slow.values())
    tr = terminalreporter
    tr.section("tier-1 budget")
    pct = 100.0 * total / TIER1_CEILING_S
    tr.write_line(f"non-slow wall time: {total:.1f}s of "
                  f"{TIER1_CEILING_S:.0f}s ceiling ({pct:.0f}%)")
    if total >= TIER1_WARN_FRAC * TIER1_CEILING_S:
        tr.write_line(
            f"WARNING: past {TIER1_WARN_FRAC:.0%} of the tier-1 ceiling "
            "— optimize or demote tests to `slow` (candidates below)")
    for nid, d in sorted(non_slow.items(), key=lambda kv: -kv[1])[:10]:
        tr.write_line(f"  {d:7.2f}s  {nid}")


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(autouse=True)
def _fixed_seed():
    from deeplearning4j_tpu.linalg import factory
    factory.setSeed(12345)
    yield
