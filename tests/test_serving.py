"""Robust inference serving (ISSUE 7): continuous batching with
deadlines, admission control, graceful degradation, and drain.

The acceptance pins:

- **Overload**: at 2x sustained capacity with a full queue, admissions
  are shed with ``ServerOverloadedError``, admitted-request p99 stays
  within 2x the uncontended p99, and no request is silently dropped or
  double-resolved (deterministic chaos test).
- **Drain**: SIGTERM during load completes the in-flight batch, fails
  queued requests with a retriable error, and the process exits 0;
  replica loss mid-serve recovers on the survivors bit-identically to a
  fresh survivor-mesh server.
- **Zero steady-state recompiles**: after ``warmup(shapes)`` every
  bucket is AOT-compiled; steady traffic at any admitted size compiles
  nothing (measured through the W201 churn detector).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import warnings
from pathlib import Path

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.faults import FaultPlan, RequestSpec, ServingLoad
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import DeviceMesh, InferenceFailedError
from deeplearning4j_tpu.parallel.wrapper import (InferenceShutdownError,
                                                 ParallelInference)
from deeplearning4j_tpu.serving import (CircuitBreaker,
                                        DeadlineExceededError, ModelServer,
                                        ServerClosedError,
                                        ServerDrainingError,
                                        ServerOverloadedError,
                                        ServerUnhealthyError, ServingError,
                                        ServingRequest)
from deeplearning4j_tpu.train import updaters
from deeplearning4j_tpu.train.resilience import (SignalPreemption,
                                                 StepPreemption)

NIN, NOUT = 4, 3
REPO = Path(__file__).resolve().parents[1]


def mlp(seed=42):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(updaters.Sgd(0.1)).list()
            .layer(DenseLayer(nOut=8, activation="relu"))
            .layer(OutputLayer(nOut=NOUT, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(NIN))
            .build())
    return MultiLayerNetwork(conf).init()


def feats(rows, seed=0):
    return np.random.RandomState(seed).randn(rows, NIN).astype(np.float32)


@pytest.fixture(scope="module")
def devices8():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return jax.devices()


@pytest.fixture()
def net():
    return mlp()


def make_server(net, **kw):
    kw.setdefault("batch_limit", 8)
    kw.setdefault("max_queue", 32)
    kw.setdefault("coalesce_ms", 1.0)
    return ModelServer(net, **kw)


class _SlowModel:
    """model.output with a fixed service time — makes capacity (and so
    queueing delay) a controlled quantity instead of scheduler noise."""

    def __init__(self, base, service_s):
        self.base = base
        self.service_s = service_s

    def output(self, x):
        time.sleep(self.service_s)
        return self.base.output(x)


class _FlakyModel:
    """model.output raises for the first ``fail`` calls after ``arm()``
    (warmup forwards stay clean), then delegates."""

    def __init__(self, base, fail=1):
        self.base = base
        self._fail = fail
        self._armed = False

    def arm(self):
        self._armed = True

    def output(self, x):
        if self._armed and self._fail > 0:
            self._fail -= 1
            raise RuntimeError("injected replica failure")
        return self.base.output(x)


# ========================================================== ServingRequest
class TestServingRequest:
    def test_exactly_once_resolution(self):
        req = ServingRequest(np.zeros((1, NIN), np.float32), None, 0.0)
        assert req._resolve(result=np.ones(3))
        assert not req._resolve(error=RuntimeError("late"))
        assert req.resolutions == 1
        np.testing.assert_array_equal(req.get(1.0), np.ones(3))

    def test_racing_resolvers_single_winner(self):
        # 16 threads race to resolve; exactly one wins, every time
        for trial in range(20):
            req = ServingRequest(np.zeros((1, NIN), np.float32), None, 0.0)
            wins = []
            start = threading.Barrier(16)

            def run(i):
                start.wait()
                if req._resolve(result=i):
                    wins.append(i)

            ts = [threading.Thread(target=run, args=(i,)) for i in range(16)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            assert len(wins) == 1 and req.resolutions == 1
            assert req.get(1.0) == wins[0]

    def test_get_timeout(self):
        req = ServingRequest(np.zeros((1, NIN), np.float32), None, 0.0)
        with pytest.raises(TimeoutError):
            req.get(0.01)

    def test_expired(self):
        req = ServingRequest(np.zeros((1, NIN), np.float32), 10.0, 9.0)
        assert not req.expired(9.5)
        assert req.expired(10.0)
        assert not ServingRequest(np.zeros((1, NIN), np.float32),
                                  None, 0.0).expired(1e9)


# ========================================================== circuit breaker
class TestCircuitBreaker:
    def _clocked(self, threshold=3, cooldown=10.0):
        t = {"now": 0.0}
        br = CircuitBreaker(threshold=threshold, cooldown=cooldown,
                            clock=lambda: t["now"])
        return br, t

    def test_opens_after_threshold(self):
        br, _ = self._clocked(threshold=3)
        br.record_failure(); br.record_failure()
        assert br.state == CircuitBreaker.CLOSED and br.admit()
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN
        assert not br.admit() and not br.allow_dispatch()

    def test_success_resets_streak(self):
        br, _ = self._clocked(threshold=3)
        br.record_failure(); br.record_failure()
        br.record_success()
        assert br.consecutive_failures == 0
        br.record_failure(); br.record_failure()
        assert br.state == CircuitBreaker.CLOSED

    def test_half_open_probe_recovers(self):
        br, t = self._clocked(threshold=1, cooldown=5.0)
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN
        assert br.retry_after() == pytest.approx(5.0)
        t["now"] = 5.0
        assert br.allow_dispatch()              # cooldown elapsed -> probe
        assert br.state == CircuitBreaker.HALF_OPEN
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED
        assert br.retry_after() is None

    def test_gauge_is_per_breaker(self):
        from deeplearning4j_tpu.serving.server import BREAKER_STATE
        a = CircuitBreaker(threshold=1, name="gauge-test-a")
        a.record_failure()
        assert a.state == CircuitBreaker.OPEN
        # constructing a second breaker must not mask A's open state
        b = CircuitBreaker(threshold=1, name="gauge-test-b")
        b.record_success()
        assert BREAKER_STATE.labels(server="gauge-test-a").value == 1.0
        assert BREAKER_STATE.labels(server="gauge-test-b").value == 0.0

    def test_half_open_probe_failure_reopens(self):
        br, t = self._clocked(threshold=5, cooldown=5.0)
        for _ in range(5):
            br.record_failure()
        t["now"] = 5.0
        assert br.admit()                       # admit flips to half-open
        assert br.state == CircuitBreaker.HALF_OPEN
        br.record_failure()                     # one probe failure reopens
        assert br.state == CircuitBreaker.OPEN
        t["now"] = 7.0
        assert br.retry_after() == pytest.approx(3.0)


# ================================================================== buckets
class TestBuckets:
    def test_ladder_doubles_from_mesh_width(self, net, devices8):
        sv = make_server(net, mesh=DeviceMesh.data_parallel(),
                         batch_limit=32)
        try:
            assert sv.buckets() == [8, 16, 32]
            assert sv._bucket_for(1) == 8
            assert sv._bucket_for(9) == 16
            assert sv._bucket_for(32) == 32
        finally:
            sv.close()

    def test_single_device_ladder(self, net):
        sv = ModelServer(net,
                         mesh=DeviceMesh.create(
                             data=1, devices=jax.devices()[:1]),
                         batch_limit=8)
        try:
            assert sv.buckets() == [1, 2, 4, 8]
        finally:
            sv.close()

    def test_every_bucket_divides_data_axis(self, net, devices8):
        sv = make_server(net, mesh=DeviceMesh.data_parallel(),
                         batch_limit=64)
        try:
            w = sv.data_width()
            assert all(b % w == 0 for b in sv.buckets())
        finally:
            sv.close()


# ======================================================= warmup / recompiles
class TestWarmup:
    def test_ready_flips_after_warmup(self, net):
        sv = make_server(net)
        try:
            assert not sv.ready and sv.state == "warming"
            sv.warmup([(NIN,)])
            assert sv.ready and sv.state == "serving"
        finally:
            sv.close()

    @pytest.mark.quick
    def test_zero_recompiles_after_warmup(self, net, devices8):
        # THE steady-state pin: warmup compiles every bucket; admitted
        # traffic at any size afterwards compiles NOTHING
        sv = make_server(net, mesh=DeviceMesh.data_parallel(),
                         batch_limit=16, coalesce_ms=0.0)
        try:
            sv.warmup([(NIN,)])
            for rows in (1, 3, 8, 11, 16, 5, 2, 16, 7):
                out = sv.output(feats(rows, seed=rows), timeout=60)
                assert out.shape == (rows, NOUT)
            assert sv.recompiles_after_warmup() == 0
        finally:
            sv.close()

    def test_oversize_request_rejected_not_compiled(self, net):
        sv = make_server(net, batch_limit=8)
        try:
            sv.warmup([(NIN,)])
            with pytest.raises(ValueError, match="exceed batch_limit"):
                sv.submit(feats(9))
            assert sv.recompiles_after_warmup() == 0
        finally:
            sv.close()

    def test_unwarmed_shape_rejected_not_compiled(self, net):
        # a novel feature shape would compile under the steady-state
        # watchdog and feed the breaker — reject it at admission
        sv = make_server(net, batch_limit=8)
        try:
            sv.warmup([(NIN,)])
            bad = np.zeros((2, NIN + 1), np.float32)
            with pytest.raises(ValueError, match="was not warmed"):
                sv.submit(bad)
            assert sv.recompiles_after_warmup() == 0
            assert sv.breaker.state == CircuitBreaker.CLOSED
        finally:
            sv.close()

    def test_warmup_runs_lint(self, net):
        sv = ModelServer(net,
                         mesh=DeviceMesh.create(
                             data=1, devices=jax.devices()[:1]),
                         batch_limit=8)
        try:
            # sabotage the ladder: a non-power-of-two duplicate-free list
            # with duplicates triggers W110 as a warning, not an error
            sv.buckets = lambda: [2, 2, 4]
            with pytest.warns(UserWarning, match="DL4J-W110"):
                sv.warmup([(NIN,)])
        finally:
            sv.close()


# ====================================================== batching / results
class TestContinuousBatching:
    def test_coalesced_results_routed_per_request(self, net):
        sv = make_server(net, batch_limit=8, coalesce_ms=20.0)
        try:
            sv.warmup([(NIN,)])
            xs = [feats(2, seed=i) for i in range(3)]
            reqs = [sv.submit(x) for x in xs]
            outs = [r.get(30.0) for r in reqs]
            for x, out in zip(xs, outs):
                np.testing.assert_allclose(
                    out, np.asarray(net.output(x)), rtol=1e-4, atol=1e-5)
            # coalescing happened: fewer batches than requests
            assert sv._batches <= 2
        finally:
            sv.close()

    def test_padding_does_not_change_results(self, net):
        sv = make_server(net, batch_limit=8, coalesce_ms=0.0)
        try:
            sv.warmup([(NIN,)])
            x = feats(3, seed=7)    # pads 3 -> bucket 4 (single device)
            np.testing.assert_allclose(sv.output(x),
                                       np.asarray(net.output(x)),
                                       rtol=1e-4, atol=1e-5)
        finally:
            sv.close()

    def test_mixed_shapes_batch_separately(self):
        # warmup() supports several feature shapes; a batch holds ONE
        # shape (mixed shapes cannot concatenate) and the serve loop
        # must survive interleaved multi-shape traffic
        conf = (NeuralNetConfiguration.Builder().seed(0).list()
                .layer(DenseLayer(nOut=8, activation="relu"))
                .layer(OutputLayer(nOut=NOUT, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(NIN)).build())
        seq_net = MultiLayerNetwork(conf).init()

        class AnyShape:
            def output(self, x):    # accepts any trailing dim by slicing
                return seq_net.output(np.asarray(x)[..., :NIN])

        sv = ModelServer(AnyShape(), batch_limit=8, max_queue=16,
                         coalesce_ms=50.0)
        try:
            sv.warmup([(NIN,), (NIN + 2,)])
            a = sv.submit(np.zeros((2, NIN), np.float32))
            b = sv.submit(np.ones((3, NIN + 2), np.float32))
            assert a.get(30.0).shape == (2, NOUT)
            assert b.get(30.0).shape == (3, NOUT)
            assert sv._worker.is_alive() and sv.healthy
            assert sv.counts["completed"] == 2
            assert sv._batches == 2          # one batch per shape
        finally:
            sv.close()

    def test_prewarmup_traffic_may_compile_under_watchdog(self, net):
        # before warmup() the first dispatch compiles; a tight
        # replica_timeout must not read that compile as a hung replica
        sv = make_server(net, coalesce_ms=0.0, replica_timeout=0.01,
                         max_retries=1)
        try:
            out = sv.output(feats(2), timeout=60)
            assert out.shape == (2, NOUT)
            assert sv.counts["completed"] == 1
            assert sv.counts.get("failed", 0) == 0
            assert sv.breaker.state == CircuitBreaker.CLOSED
        finally:
            sv.close()

    def test_occupancy_and_batch_metrics(self, net):
        from deeplearning4j_tpu.serving.server import BATCHES, OCCUPANCY
        before = (BATCHES.value, OCCUPANCY.count)
        sv = make_server(net, coalesce_ms=0.0)
        try:
            sv.warmup([(NIN,)])
            sv.output(feats(2))
            assert BATCHES.value == before[0] + 1
            assert OCCUPANCY.count == before[1] + 1
        finally:
            sv.close()


# ================================================================ deadlines
class TestDeadlines:
    def test_expired_while_queued_is_shed(self, net):
        sv = make_server(net)
        try:
            sv.warmup([(NIN,)])
            req = sv.submit(feats(2), deadline=0.0)
            with pytest.raises(DeadlineExceededError) as ei:
                req.get(10.0)
            assert not ei.value.retriable          # deadline is gone
            assert sv.counts["shed_deadline"] == 1
        finally:
            sv.close()

    def test_shed_request_never_also_completed(self, net):
        # the satellite pin: shed XOR completed, never both
        sv = make_server(net, coalesce_ms=5.0)
        try:
            sv.warmup([(NIN,)])
            reqs = [sv.submit(feats(1, seed=i),
                              deadline=0.0 if i % 2 else 5.0)
                    for i in range(10)]
            outcomes = []
            for r in reqs:
                try:
                    outcomes.append(("ok", r.get(30.0)))
                except DeadlineExceededError:
                    outcomes.append(("shed", None))
            assert all(r.resolutions == 1 for r in reqs)
            assert [o for o, _ in outcomes[1::2]] == ["shed"] * 5
            assert [o for o, _ in outcomes[0::2]] == ["ok"] * 5
        finally:
            sv.close()

    def test_slow_client_does_not_rot_the_batch(self, net):
        # a deadline-0 head-of-line request is reclaimed; the live one
        # behind it still dispatches in the same build pass
        sv = make_server(net, coalesce_ms=50.0, batch_limit=2)
        try:
            sv.warmup([(NIN,)])
            dead = sv.submit(feats(1, seed=1), deadline=0.0)
            live = sv.submit(feats(2, seed=2))   # fills the batch alone
            out = live.get(30.0)
            assert out.shape == (2, NOUT)
            with pytest.raises(DeadlineExceededError):
                dead.get(1.0)
        finally:
            sv.close()

    def test_expired_behind_unexpired_head_shed_while_breaker_open(
            self, net):
        # breaker open -> nothing dispatches; an expired tight-deadline
        # request BEHIND an unexpired head must still shed at its
        # deadline, not when the cooldown elapses
        sv = make_server(net, coalesce_ms=0.0, breaker_cooldown=60.0)
        try:
            sv.warmup([(NIN,)])
            for _ in range(sv.breaker.threshold):
                sv.breaker.record_failure()
            assert sv.breaker.state == CircuitBreaker.OPEN
            # queue: loose head, tight behind it (submit bypasses admit
            # by enqueueing directly — admission rejects while open)
            now = time.monotonic()
            loose = ServingRequest(feats(1, seed=1), now + 30.0, now)
            tight = ServingRequest(feats(1, seed=2), now + 0.05, now)
            with sv._cond:
                sv._dq.append(loose)
                sv._dq.append(tight)
                sv._cond.notify()
            with pytest.raises(DeadlineExceededError):
                tight.get(5.0)
            assert sv.breaker.state == CircuitBreaker.OPEN   # still open
            assert not loose.done()                # head stays queued
        finally:
            sv.close()

    def test_default_deadline_applied(self, net):
        sv = make_server(net, default_deadline=0.0)
        try:
            sv.warmup([(NIN,)])
            with pytest.raises(DeadlineExceededError):
                sv.submit(feats(1)).get(10.0)
        finally:
            sv.close()


# ======================================================== admission control
class TestAdmissionControl:
    def test_full_queue_sheds_with_structured_error(self, net):
        sv = ModelServer(_SlowModel(net, 0.2), batch_limit=1, max_queue=2,
                         coalesce_ms=0.0)
        try:
            sv.warmup([(NIN,)])
            reqs, shed = [], 0
            for i in range(12):
                try:
                    reqs.append(sv.submit(feats(1, seed=i)))
                except ServerOverloadedError as e:
                    shed += 1
                    assert e.retriable and e.max_queue == 2
            assert shed > 0
            assert sv.counts["shed_overload"] == shed
            for r in reqs:                       # admitted => answered
                assert r.get(30.0).shape == (1, NOUT)
        finally:
            sv.close()

    def test_submit_never_blocks(self, net):
        sv = ModelServer(_SlowModel(net, 0.5), batch_limit=1, max_queue=1,
                         coalesce_ms=0.0)
        try:
            sv.warmup([(NIN,)])
            t0 = time.monotonic()
            admitted = 0
            for i in range(20):
                try:
                    sv.submit(feats(1, seed=i))
                    admitted += 1
                except ServerOverloadedError:
                    pass
            # 20 submits against a 0.5s/batch server return ~instantly
            assert time.monotonic() - t0 < 0.4
            assert admitted < 20
        finally:
            sv.close()

    def test_closed_server_rejects(self, net):
        sv = make_server(net)
        sv.warmup([(NIN,)])
        sv.close()
        with pytest.raises(ServerClosedError) as ei:
            sv.submit(feats(1))
        assert ei.value.retriable


# ==================================================== graceful degradation
class TestGracefulDegradation:
    def test_transient_replica_fault_retried(self, net, devices8):
        plan = FaultPlan(seed=3, serve_fail_at=[2])
        sv = make_server(net, mesh=DeviceMesh.data_parallel(),
                         batch_limit=8, coalesce_ms=0.0, faults=plan,
                         max_retries=2)
        try:
            sv.warmup([(NIN,)])
            x = feats(8, seed=1)
            sv.output(x)                          # batch 1: clean
            with pytest.warns(UserWarning, match="dispatch failure"):
                out = sv.output(x, timeout=60)    # batch 2: fault + retry
            np.testing.assert_allclose(out, np.asarray(net.output(x)),
                                       rtol=1e-4, atol=1e-5)
            assert sv.counts["completed"] == 2
            assert sv.breaker.state == CircuitBreaker.CLOSED
        finally:
            sv.close()

    def test_replica_loss_shrinks_and_matches_fresh_survivor_server(
            self, devices8):
        # THE degradation pin: after losing half the mesh mid-serve, the
        # shrunk server's outputs are bit-identical to a fresh server
        # built on the survivor mesh
        net = mlp()
        plan = FaultPlan(seed=4, serve_device_loss_at_batch=2,
                         lose_devices=[4, 5, 6, 7])
        sv = make_server(net, mesh=DeviceMesh.data_parallel(),
                         batch_limit=16, coalesce_ms=0.0, faults=plan,
                         max_retries=2)
        fresh = None
        try:
            sv.warmup([(NIN,)])
            x = feats(8, seed=2)
            sv.output(x)                          # batch 1 on 8 devices
            with pytest.warns(UserWarning, match="dropping dead device"):
                y = sv.output(x, timeout=120)     # batch 2: loss -> shrink
            assert {d.id for d in sv.mesh.devices} == {0, 1, 2, 3}
            # the re-warm restored the zero-recompile baseline
            assert sv.recompiles_after_warmup() == 0
            mesh4 = DeviceMesh.create(data=4, devices=jax.devices()[:4])
            fresh = make_server(net, mesh=mesh4, batch_limit=16,
                                coalesce_ms=0.0)
            fresh.warmup([(NIN,)])
            np.testing.assert_array_equal(y, fresh.output(x, timeout=60))
            # steady state on the survivors stays compile-free too
            sv.output(feats(16, seed=3), timeout=60)
            assert sv.recompiles_after_warmup() == 0
        finally:
            sv.close()
            if fresh is not None:
                fresh.close()

    def test_replica_loss_to_non_divisor_survivor_count(self, devices8):
        # losing 1 of 8 devices leaves 7 survivors — the OLD bucket
        # ladder (multiples of 8) cannot shard on the new data axis, so
        # the retry must RE-pad the live rows to the survivor ladder
        net = mlp()
        plan = FaultPlan(seed=6, serve_device_loss_at_batch=2,
                         lose_devices=[7])
        sv = make_server(net, mesh=DeviceMesh.data_parallel(),
                         batch_limit=16, coalesce_ms=0.0, faults=plan,
                         max_retries=2)
        try:
            sv.warmup([(NIN,)])
            x = feats(6, seed=5)
            sv.output(x)                          # batch 1 on 8 devices
            with pytest.warns(UserWarning, match="dropping dead device"):
                y = sv.output(x, timeout=120)     # batch 2: 8 -> 7
            assert len(sv.mesh.devices) == 7
            assert sv.buckets()[0] == 7
            np.testing.assert_allclose(y, np.asarray(net.output(x)),
                                       rtol=1e-4, atol=1e-5)
            assert sv.recompiles_after_warmup() == 0   # re-warm re-based
        finally:
            sv.close()

    def test_shrink_without_rewarm_compiles_unsupervised(self, devices8):
        # rewarm_on_shrink=False: the retry legitimately compiles ONE
        # program on the shrunk mesh; a tight replica_timeout must not
        # flag that compile as a hung replica
        net = mlp()
        plan = FaultPlan(seed=8, serve_device_loss_at_batch=1,
                         lose_devices=[4, 5, 6, 7])
        sv = make_server(net, mesh=DeviceMesh.data_parallel(),
                         batch_limit=16, coalesce_ms=0.0, faults=plan,
                         max_retries=2, replica_timeout=0.75,
                         rewarm_on_shrink=False)
        try:
            sv.warmup([(NIN,)])
            x = feats(8, seed=6)
            with pytest.warns(UserWarning, match="dropping dead device"):
                y = sv.output(x, timeout=120)
            np.testing.assert_allclose(y, np.asarray(net.output(x)),
                                       rtol=1e-4, atol=1e-5)
            assert len(sv.mesh.devices) == 4
            assert sv.counts["completed"] == 1
        finally:
            sv.close()

    def test_breaker_trips_then_half_open_probe_recovers(self, net):
        clock = {"now": 0.0}
        flaky = _FlakyModel(net, fail=6)   # 2 batches x 3 attempts each
        sv = ModelServer(flaky, batch_limit=2, max_queue=8, coalesce_ms=0.0,
                         max_retries=2, breaker_threshold=2,
                         breaker_cooldown=30.0,
                         _breaker_clock=lambda: clock["now"])
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                sv.warmup([(NIN,)])
                flaky.arm()
                r1 = sv.submit(feats(1, seed=1))
                with pytest.raises(InferenceFailedError):
                    r1.get(30.0)
                r2 = sv.submit(feats(1, seed=2))
                with pytest.raises(InferenceFailedError):
                    r2.get(30.0)
            assert sv.breaker.state == CircuitBreaker.OPEN
            assert not sv.healthy
            with pytest.raises(ServerUnhealthyError) as ei:
                sv.submit(feats(1, seed=3))
            assert ei.value.retriable
            assert ei.value.retry_after == pytest.approx(30.0, abs=1.0)
            assert sv.counts["rejected_unhealthy"] == 1
            # cooldown elapses -> half-open admits the probe; the model
            # has recovered, so the probe closes the breaker
            clock["now"] = 31.0
            out = sv.output(feats(2, seed=4), timeout=30)
            assert out.shape == (2, NOUT)
            assert sv.breaker.state == CircuitBreaker.CLOSED
            assert sv.healthy
        finally:
            sv.close()

    def test_failed_batch_resolves_every_request_exactly_once(self, net):
        flaky = _FlakyModel(net, fail=99)
        sv = ModelServer(flaky, batch_limit=4,
                         max_queue=8, coalesce_ms=20.0, max_retries=1)
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                sv.warmup([(NIN,)])
                flaky.arm()
                reqs = [sv.submit(feats(1, seed=i)) for i in range(3)]
                for r in reqs:
                    with pytest.raises(InferenceFailedError):
                        r.get(30.0)
            assert all(r.resolutions == 1 for r in reqs)
            assert sv.counts["failed"] == 3
        finally:
            sv.close()


# ==================================================================== drain
class TestDrain:
    def test_drain_fails_queued_with_retriable_error(self, net):
        sv = ModelServer(_SlowModel(net, 0.2), batch_limit=1, max_queue=16,
                         coalesce_ms=0.0)
        sv.warmup([(NIN,)])
        reqs = [sv.submit(feats(1, seed=i)) for i in range(6)]
        sv.drain()
        outcomes = {"ok": 0, "drained": 0}
        for r in reqs:
            try:
                r.get(30.0)
                outcomes["ok"] += 1
            except ServerDrainingError as e:
                assert e.retriable
                outcomes["drained"] += 1
        # the in-flight work completed; the queued tail was failed fast
        assert outcomes["ok"] >= 1
        assert outcomes["drained"] >= 1
        assert all(r.resolutions == 1 for r in reqs)
        assert not sv.ready
        assert sv.state == "draining"
        sv.close()
        assert sv.state == "closed"

    def test_admissions_rejected_while_draining(self, net):
        sv = make_server(net)
        sv.warmup([(NIN,)])
        sv.drain()
        with pytest.raises(ServerDrainingError):
            sv.submit(feats(1))
        assert sv.counts["shed_draining"] >= 1
        sv.close()

    def test_step_preemption_triggers_drain(self, net):
        sv = make_server(net, coalesce_ms=0.0, preemption=StepPreemption(1))
        try:
            sv.warmup([(NIN,)])
            assert sv.output(feats(2)).shape == (2, NOUT)
            deadline = time.monotonic() + 5.0
            while sv.state != "draining" and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sv.state == "draining"
            with pytest.raises(ServerDrainingError):
                sv.submit(feats(1))
        finally:
            sv.close()

    def test_drain_idempotent_and_close_releases(self, net):
        sv = make_server(net, preemption=StepPreemption(10 ** 9))
        sv.warmup([(NIN,)])
        sv.drain()
        sv.drain()
        sv.close()
        sv.close()
        assert sv.state == "closed"
        # healthy stays true after a clean close (the loop didn't die)
        assert sv.healthy

    def test_sigterm_drains_and_process_exits_zero(self, tmp_path):
        # THE drain pin, end to end: a real process under load takes
        # SIGTERM, completes in-flight work, fails the queue with the
        # retriable drain error, and exits 0
        script = tmp_path / "serve_sigterm.py"
        script.write_text(
            "import os, sys, time, threading\n"
            "import numpy as np\n"
            "from deeplearning4j_tpu.nn import (InputType,\n"
            "    MultiLayerNetwork, NeuralNetConfiguration)\n"
            "from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer\n"
            "from deeplearning4j_tpu.serving import (ModelServer,\n"
            "    ServerDrainingError)\n"
            "conf = (NeuralNetConfiguration.Builder().seed(0).list()\n"
            "        .layer(DenseLayer(nOut=8, activation='relu'))\n"
            "        .layer(OutputLayer(nOut=3, lossFunction='mcxent',\n"
            "                           activation='softmax'))\n"
            "        .setInputType(InputType.feedForward(4)).build())\n"
            "net = MultiLayerNetwork(conf).init()\n"
            "class Slow:\n"
            "    def output(self, x):\n"
            "        time.sleep(0.1)\n"
            "        return net.output(x)\n"
            "sv = ModelServer(Slow(), batch_limit=1, max_queue=64,\n"
            "                 coalesce_ms=0.0, preemption=True)\n"
            "sv.warmup([(4,)])\n"
            "reqs = [sv.submit(np.zeros((1, 4), np.float32))\n"
            "        for _ in range(20)]\n"
            "print('READY', flush=True)\n"
            "os.kill(os.getpid(), 15)  # SIGTERM mid-load\n"
            "ok = drained = 0\n"
            "for r in reqs:\n"
            "    try:\n"
            "        r.get(30.0); ok += 1\n"
            "    except ServerDrainingError:\n"
            "        drained += 1\n"
            "assert ok + drained == 20, (ok, drained)\n"
            "assert drained >= 1, 'queued tail must be drained'\n"
            "assert all(r.resolutions == 1 for r in reqs)\n"
            "sv.close()\n"
            "print('DRAINED', ok, drained, flush=True)\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=str(REPO))
        proc = subprocess.run([sys.executable, str(script)],
                              capture_output=True, text=True, timeout=180,
                              env=env, cwd=str(REPO))
        assert proc.returncode == 0, proc.stderr
        assert "DRAINED" in proc.stdout


# ============================================================ health surface
class TestHealthSurface:
    def _get(self, port, path):
        try:
            r = urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5)
            return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_healthz_readyz_lifecycle(self, net):
        from deeplearning4j_tpu.ui.server import UIServer
        ui = UIServer(port=0)
        sv = make_server(net)
        try:
            ui.attach_serving(sv)
            code, body = self._get(ui.port, "/readyz")
            assert code == 503 and body["state"] == "warming"
            sv.warmup([(NIN,)])
            code, body = self._get(ui.port, "/readyz")
            assert code == 200 and body["ready"]
            code, body = self._get(ui.port, "/healthz")
            assert code == 200 and body["breaker"] == "closed"
            sv.drain()
            code, body = self._get(ui.port, "/readyz")
            assert code == 503 and body["state"] == "draining"
            # drained-but-alive is still healthy (liveness != readiness)
            code, _ = self._get(ui.port, "/healthz")
            assert code == 200
        finally:
            sv.close()
            ui.stop()

    def test_healthz_unhealthy_when_breaker_open(self, net):
        from deeplearning4j_tpu.ui.server import UIServer
        ui = UIServer(port=0)
        sv = make_server(net)
        try:
            ui.attach_serving(sv)
            sv.warmup([(NIN,)])
            for _ in range(sv.breaker.threshold):
                sv.breaker.record_failure()
            code, body = self._get(ui.port, "/healthz")
            assert code == 503 and body["breaker"] == "open"
        finally:
            sv.close()
            ui.stop()

    def test_no_server_attached(self):
        from deeplearning4j_tpu.ui.server import UIServer
        ui = UIServer(port=0)
        try:
            ui._ensure_httpd()
            code, _ = self._get(ui.port, "/healthz")
            assert code == 200               # process liveness
            code, _ = self._get(ui.port, "/readyz")
            assert code == 503               # but not ready to serve
        finally:
            ui.stop()

    def test_metrics_survive_detach(self):
        # the satellite pin: detach() removes the dashboard storage but
        # /metrics (and the server) stay live
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.ui.stats import InMemoryStatsStorage
        ui = UIServer(port=0).attach(InMemoryStatsStorage())
        try:
            code, _ = self._get(ui.port, "/api/sessions")
            assert code == 200
            ui.detach()
            m = urllib.request.urlopen(
                f"http://127.0.0.1:{ui.port}/metrics", timeout=5).read()
            assert b"dl4j_serving_requests_total" in m
            code, body = self._get(ui.port, "/api/sessions")
            assert code == 503 and "no stats storage" in body["error"]
        finally:
            ui.stop()

    def test_reattach_swaps_storage_atomically(self):
        from deeplearning4j_tpu.ui.server import UIServer, _Handler
        from deeplearning4j_tpu.ui.stats import InMemoryStatsStorage
        st1, st2 = InMemoryStatsStorage(), InMemoryStatsStorage()
        ui = UIServer(port=0).attach(st1)
        try:
            assert ui._httpd.dl4j_storage is st1
            ui.attach(st2)
            assert ui._httpd.dl4j_storage is st2
            # the fix: no shared class attribute is ever written
            assert not any("storage" in vars(k)
                           for k in _Handler.__mro__ if k is not object) \
                or isinstance(vars(_Handler).get("storage"), property)
        finally:
            ui.stop()

    def test_stats_snapshot(self, net):
        sv = make_server(net, coalesce_ms=0.0)
        try:
            sv.warmup([(NIN,)])
            sv.output(feats(2))
            st = sv.stats()
            assert st["state"] == "serving" and st["ready"]
            assert st["counts"]["completed"] >= 1
            assert st["recompiles_after_warmup"] == 0
            assert st["latency_p50"] is not None
            assert st["latency_p99"] >= st["latency_p50"]
        finally:
            sv.close()


# ===================================================== ParallelInference fix
class TestParallelInferenceShutdown:
    def test_close_fails_pending_requests(self, net):
        pi = ParallelInference(_SlowModel(net, 0.3), batch_limit=1,
                               queue_timeout_ms=1.0)
        reqs = [pi.submit(feats(1, seed=i)) for i in range(5)]
        pi.close()
        t0 = time.monotonic()
        outcomes = {"ok": 0, "shutdown": 0}
        for r in reqs:
            try:
                r.get(timeout=10.0)
                outcomes["ok"] += 1
            except InferenceShutdownError as e:
                assert e.retriable
                outcomes["shutdown"] += 1
        # pending requests failed IMMEDIATELY, not after their own
        # get(timeout) expired
        assert time.monotonic() - t0 < 5.0
        assert outcomes["shutdown"] >= 1

    def test_submit_after_close_raises(self, net):
        pi = ParallelInference(net, batch_limit=4)
        pi.close()
        with pytest.raises(InferenceShutdownError):
            pi.submit(feats(1))

    def test_bounded_queue_sheds(self, net):
        pi = ParallelInference(_SlowModel(net, 0.3), batch_limit=1,
                               queue_timeout_ms=1.0, max_queue=2)
        try:
            shed = 0
            for i in range(10):
                try:
                    pi.submit(feats(1, seed=i))
                except ServerOverloadedError:
                    shed += 1
            assert shed > 0
        finally:
            pi.close()

    def test_context_manager(self, net):
        with ParallelInference(net, batch_limit=4) as pi:
            out = pi.output(feats(2), timeout=60)
            assert out.shape == (2, NOUT)
        assert pi._shutdown
        pi.close()      # idempotent

    def test_shutdown_alias(self, net):
        pi = ParallelInference(net, batch_limit=4)
        pi.shutdown()
        with pytest.raises(InferenceShutdownError):
            pi.submit(feats(1))


# ============================================================== serving lint
class TestServingLint:
    def _conf(self):
        return (NeuralNetConfiguration.Builder().seed(0).list()
                .layer(DenseLayer(nOut=8, activation="relu"))
                .layer(OutputLayer(nOut=NOUT, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(NIN)).build())

    def test_clean_bill(self):
        from deeplearning4j_tpu.analysis import lint_serving
        report = lint_serving(self._conf(), [8, 16, 32],
                              mesh={"data": 8}, shapes=[(NIN,)],
                              hbm_gb=16.0)
        assert report.codes() == []

    def test_e110_bucket_mesh_mismatch(self):
        from deeplearning4j_tpu.analysis import lint_serving
        report = lint_serving(self._conf(), [8, 12], mesh={"data": 8})
        assert "DL4J-E110" in report.codes()
        with pytest.raises(Exception):
            report.raise_if_errors()

    def test_e111_hbm_budget(self):
        from deeplearning4j_tpu.analysis import lint_serving
        big = (NeuralNetConfiguration.Builder().seed(0).list()
               .layer(DenseLayer(nOut=4096, activation="relu"))
               .layer(OutputLayer(nOut=4096, lossFunction="mse",
                                  activation="identity"))
               .setInputType(InputType.feedForward(4096)).build())
        report = lint_serving(big, [64], mesh={"data": 1},
                              shapes=[(4096,)], hbm_gb=0.05)
        assert "DL4J-E111" in report.codes()

    def test_w110_pathological_ladder(self):
        from deeplearning4j_tpu.analysis import lint_serving
        assert "DL4J-W110" in lint_serving(
            self._conf(), [4, 4, 8], mesh={"data": 1}).codes()
        assert "DL4J-W110" in lint_serving(
            self._conf(), list(range(1, 11)), mesh={"data": 1}).codes()

    def test_no_hbm_skips_budget(self):
        from deeplearning4j_tpu.analysis import lint_serving
        report = lint_serving(self._conf(), [8], mesh={"data": 1})
        assert "DL4J-E111" not in report.codes()

    def test_server_validate_wires_lint(self, net, devices8):
        sv = make_server(net, mesh=DeviceMesh.data_parallel())
        try:
            assert sv.validate().codes() == []
            assert "DL4J-E111" in sv.validate(shapes=[(NIN,)],
                                              hbm_gb=1e-9).codes()
        finally:
            sv.close()


# ============================================================== serving load
class TestServingLoad:
    def test_seeded_deterministic(self):
        a = ServingLoad.seeded(7, mix="steady", n=50)
        b = ServingLoad.seeded(7, mix="steady", n=50)
        assert [(s.at, s.rows, s.deadline) for s in a] == \
               [(s.at, s.rows, s.deadline) for s in b]
        c = ServingLoad.seeded(8, mix="steady", n=50)
        assert [(s.at, s.rows) for s in a] != [(s.at, s.rows) for s in c]

    def test_mixes(self):
        steady = ServingLoad.seeded(1, mix="steady", n=100)
        assert len(steady) == 100
        assert all(s.deadline is None for s in steady)
        burst = ServingLoad.seeded(1, mix="burst", n=100, n_bursts=2,
                                   burst_size=30)
        assert len(burst) == 100
        ats = [s.at for s in burst]
        assert ats == sorted(ats)
        # the volleys: some arrival time repeats burst_size times
        from collections import Counter
        assert max(Counter(ats).values()) >= 30
        # volley plans larger than n clamp instead of over-generating
        assert len(ServingLoad.seeded(0, mix="burst", n=30, n_bursts=4,
                                      burst_size=100)) == 30
        assert len(ServingLoad.seeded(0, mix="burst", n=2,
                                      n_bursts=4, burst_size=8)) == 2
        dl = ServingLoad.seeded(1, mix="deadline", n=100,
                                tight_deadline=0.001, loose_deadline=1.0,
                                deadline_frac=0.5)
        tight = sum(1 for s in dl if s.deadline == 0.001)
        assert 20 < tight < 80

    def test_unknown_mix_rejected(self):
        with pytest.raises(ValueError, match="unknown mix"):
            ServingLoad.seeded(0, mix="tsunami")

    def test_hand_built_load(self):
        load = ServingLoad([RequestSpec(0.0, 2, None),
                            RequestSpec(0.01, 1, 0.5)])
        assert len(load) == 2
        assert load.duration() == pytest.approx(0.01)
        assert "rows=2" in repr(load.specs[0])

    def test_replay_captures_rejections(self, net):
        sv = ModelServer(_SlowModel(net, 0.05), batch_limit=1, max_queue=1,
                         coalesce_ms=0.0)
        try:
            sv.warmup([(NIN,)])
            load = ServingLoad.seeded(2, mix="burst", n=30, rps=2000.0,
                                      n_bursts=1, burst_size=25,
                                      max_rows=1)
            out = load.replay(sv.submit, (NIN,))
            assert len(out) == 30
            rejected = [e for _, e in out
                        if isinstance(e, ServerOverloadedError)]
            handles = [h for _, h in out if isinstance(h, ServingRequest)]
            assert rejected and handles
            assert len(rejected) + len(handles) == 30
        finally:
            sv.close()

    def test_seeded_serving_plan(self):
        plan = FaultPlan.seeded_serving(11, horizon=20, n_fail=2, n_slow=1,
                                        device_loss=2,
                                        device_pool=range(8))
        assert len(plan.serve_fail_at) == 2
        assert len(plan.slow_replica_at) == 1
        assert plan.serve_device_loss_at_batch >= 2
        assert len(plan.lose_devices) == 2
        again = FaultPlan.seeded_serving(11, horizon=20, n_fail=2, n_slow=1,
                                         device_loss=2,
                                         device_pool=range(8))
        assert plan.serve_fail_at == again.serve_fail_at
        assert plan.lose_devices == again.lose_devices
        with pytest.raises(ValueError, match="whole"):
            FaultPlan.seeded_serving(0, 10, device_loss=2,
                                     device_pool=[1, 2])


# ============================================================= histogram q
class TestHistogramQuantile:
    def test_quantiles(self):
        from deeplearning4j_tpu.profiler.metrics import Histogram
        h = Histogram("q_test_hist", "d", buckets=(1.0, 2.0, 4.0, 8.0))
        assert h.quantile(0.5) is None
        for v in (0.5, 1.5, 1.5, 3.0):
            h.observe(v)
        assert 0.0 <= h.quantile(0.25) <= 1.0
        assert 1.0 <= h.quantile(0.5) <= 2.0
        assert 2.0 <= h.quantile(0.99) <= 4.0
        with pytest.raises(ValueError):
            h.quantile(1.5)

    def test_inf_bucket_clamps(self):
        from deeplearning4j_tpu.profiler.metrics import Histogram
        h = Histogram("q_test_inf", "d", buckets=(1.0, 2.0))
        h.observe(100.0)
        assert h.quantile(0.99) == 2.0


# ============================================================== preemption
class TestSignalPreemptionCallback:
    def test_on_request_callback_fires(self):
        fired = threading.Event()
        sp = SignalPreemption(signals=(signal.SIGUSR1,),
                              on_request=fired.set)
        assert sp.install()
        try:
            os.kill(os.getpid(), signal.SIGUSR1)
            deadline = time.monotonic() + 5.0
            while not fired.is_set() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert fired.is_set()
            assert sp.requested(0)
        finally:
            sp.uninstall()

    def test_failing_callback_swallowed(self):
        def boom():
            raise RuntimeError("callback bug")
        sp = SignalPreemption(signals=(signal.SIGUSR1,), on_request=boom)
        assert sp.install()
        try:
            os.kill(os.getpid(), signal.SIGUSR1)
            deadline = time.monotonic() + 5.0
            while not sp.requested(0) and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sp.requested(0)    # the flag still set despite the raise
        finally:
            sp.uninstall()


# ==================================================================== chaos
@pytest.mark.chaos
class TestServingChaos:
    def test_overload_pin_2x_capacity(self, net):
        # THE overload pin: sustained 2x capacity against a full queue.
        # Every admission outcome is structured (completed | overload |
        # deadline), nothing is dropped or double-resolved, and the
        # bounded queue keeps admitted-request p99 within 2x the
        # uncontended p99.
        service = 0.05
        sv = ModelServer(_SlowModel(net, service), batch_limit=4,
                         max_queue=4, coalesce_ms=1.0)
        try:
            sv.warmup([(NIN,)])
            # uncontended p99: one request at a time
            uncontended = []
            for i in range(5):
                r = sv.submit(feats(1, seed=i))
                r.get(30.0)
                uncontended.append(r.resolved_at - r.enqueued_at)
            p99_unc = sorted(uncontended)[-1]
            # sustained 2x capacity: capacity = batch_limit/service rows/s
            capacity_rps = sv.batch_limit / service
            load = ServingLoad.seeded(21, mix="steady", n=120,
                                      rps=2 * capacity_rps, max_rows=1)
            results = load.replay(sv.submit, (NIN,))
            latencies, shed_overload, shed_deadline, failed = [], 0, 0, 0
            for spec, h in results:
                if isinstance(h, ServerOverloadedError):
                    shed_overload += 1
                    continue
                assert isinstance(h, ServingRequest), h
                try:
                    h.get(30.0)
                    # resolved_at is stamped by the server at completion,
                    # so this measures true request latency, not how long
                    # this sequential collection loop took to reach h
                    latencies.append(h.resolved_at - h.enqueued_at)
                except DeadlineExceededError:
                    shed_deadline += 1
                except ServingError:
                    failed += 1
            # accounting: every request has exactly one outcome
            assert shed_overload + shed_deadline + failed \
                + len(latencies) == 120
            handles = [h for _, h in results
                       if isinstance(h, ServingRequest)]
            assert all(h.resolutions == 1 for h in handles)
            # 2x load against a 1-batch queue MUST shed
            assert shed_overload > 0
            assert failed == 0
            # bounded queue bounds the wait: at most ~(1 queued batch +
            # in-flight) ahead of any admitted request
            p99_adm = sorted(latencies)[max(
                int(len(latencies) * 0.99) - 1, 0)]
            assert p99_adm <= 2 * p99_unc + 4 * service, \
                (p99_adm, p99_unc)
        finally:
            sv.close()

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_burst_sweep_no_double_resolution(self, net, seed):
        # the deadline-semantics satellite under the burst sweep: shed
        # XOR completed for every request, across seeds
        sv = ModelServer(_SlowModel(net, 0.01), batch_limit=4, max_queue=8,
                         coalesce_ms=0.5, default_deadline=0.08)
        try:
            sv.warmup([(NIN,)])
            load = ServingLoad.seeded(seed, mix="burst", n=60, rps=300.0,
                                      n_bursts=3, burst_size=15, max_rows=2)
            results = load.replay(sv.submit, (NIN,))
            outcomes = {"completed": 0, "deadline": 0, "overload": 0}
            for _, h in results:
                if isinstance(h, ServerOverloadedError):
                    outcomes["overload"] += 1
                    continue
                try:
                    h.get(30.0)
                    outcomes["completed"] += 1
                except DeadlineExceededError:
                    outcomes["deadline"] += 1
            assert sum(outcomes.values()) == 60
            handles = [h for _, h in results
                       if isinstance(h, ServingRequest)]
            assert all(h.resolutions == 1 for h in handles)
            assert outcomes["completed"] > 0
        finally:
            sv.close()

    @pytest.mark.parametrize("seed", [3, 4])
    def test_deadline_storm_sweep(self, net, seed):
        sv = ModelServer(_SlowModel(net, 0.03), batch_limit=2, max_queue=64,
                         coalesce_ms=0.5)
        try:
            sv.warmup([(NIN,)])
            load = ServingLoad.seeded(seed, mix="deadline", n=40,
                                      rps=200.0, max_rows=1,
                                      tight_deadline=0.002,
                                      loose_deadline=10.0)
            results = load.replay(sv.submit, (NIN,),
                                  rng_seed=seed)
            done = shed = 0
            for spec, h in results:
                assert isinstance(h, ServingRequest)
                try:
                    h.get(30.0)
                    done += 1
                except DeadlineExceededError:
                    shed += 1
                    assert spec.deadline == 0.002    # only tight ones shed
            assert done + shed == 40
            assert shed > 0 and done > 0
            # loose-deadline requests were NOT starved by the storm
            loose = [h for s, h in results if s.deadline == 10.0]
            assert all(h.resolutions == 1 and h._error is None
                       for h in loose)
        finally:
            sv.close()

    def test_seeded_fault_sweep_recovers(self, net, devices8):
        # transient fault + slow forward + device loss in one seeded
        # plan: the server ends healthy on the survivor mesh with every
        # request answered
        plan = FaultPlan.seeded_serving(17, horizon=8, n_fail=1,
                                        device_loss=4,
                                        device_pool=range(8))
        sv = make_server(net, mesh=DeviceMesh.data_parallel(),
                         batch_limit=8, coalesce_ms=0.0, faults=plan,
                         max_retries=3)
        try:
            sv.warmup([(NIN,)])
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                for b in range(10):
                    out = sv.output(feats(8, seed=b), timeout=120)
                    assert out.shape == (8, NOUT)
            assert sv.counts["completed"] == 10
            assert sv.counts.get("failed", 0) == 0
            assert sv.healthy
            assert len(sv.mesh.devices) == 4
        finally:
            sv.close()


# ===================================================== forward adapters (I12)
class TestForwardAdapters:
    """ISSUE 12 satellite: the server stops assuming ``model.output`` —
    any callable forward serves, including multi-output graphs and
    SameDiff (imported-model) graphs."""

    def test_plain_callable_forward(self):
        import jax.numpy as jnp
        sv = ModelServer(lambda x: jnp.tanh(x) * 2.0, batch_limit=8,
                         coalesce_ms=0.5)
        try:
            sv.warmup([(NIN,)])
            x = feats(3)
            np.testing.assert_allclose(sv.output(x), np.tanh(x) * 2.0,
                                       rtol=1e-6)
            assert sv.recompiles_after_warmup() == 0
        finally:
            sv.close()

    def _two_headed_graph(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        g = (NeuralNetConfiguration.Builder().seed(2)
             .updater(updaters.Sgd(0.1))
             .graphBuilder()
             .addInputs("x")
             .setInputTypes(InputType.feedForward(NIN)))
        g.addLayer("trunk", DenseLayer(nOut=8, activation="relu"), "x")
        g.addLayer("out1", OutputLayer(nOut=2, lossFunction="mcxent",
                                       activation="softmax"), "trunk")
        g.addLayer("out2", OutputLayer(nOut=1, lossFunction="mse",
                                       activation="identity"), "trunk")
        g.setOutputs("out1", "out2")
        return ComputationGraph(g.build()).init()

    def test_graph_multi_output_serves_as_tuple(self):
        gnet = self._two_headed_graph()
        sv = ModelServer(gnet, batch_limit=8, coalesce_ms=0.5)
        try:
            sv.warmup([(NIN,)])
            x = feats(3)
            o1, o2 = sv.output(x)
            assert o1.shape == (3, 2) and o2.shape == (3, 1)
            w1, w2 = gnet.output(x)
            # padded-bucket dispatch (8 rows) vs the direct 3-row call
            # may differ by float tiling — value equality, not bitwise
            np.testing.assert_allclose(o1, np.asarray(w1), rtol=1e-5)
            np.testing.assert_allclose(o2, np.asarray(w2), rtol=1e-5)
        finally:
            sv.close()

    def test_graph_multi_output_rows_split_exactly(self):
        # two concurrent requests coalesce into ONE dispatch; each gets
        # ITS rows of BOTH heads back
        gnet = self._two_headed_graph()
        sv = ModelServer(gnet, batch_limit=8, coalesce_ms=20.0)
        try:
            sv.warmup([(NIN,)])
            ra = sv.submit(feats(2, seed=1))
            rb = sv.submit(feats(3, seed=2))
            a1, a2 = ra.get(30.0)
            b1, b2 = rb.get(30.0)
            assert a1.shape == (2, 2) and a2.shape == (2, 1)
            assert b1.shape == (3, 2) and b2.shape == (3, 1)
            w1, w2 = gnet.output(feats(2, seed=1))
            np.testing.assert_allclose(a1, np.asarray(w1), rtol=1e-5)
        finally:
            sv.close()

    def _samediff(self):
        from deeplearning4j_tpu.autodiff import SameDiff
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, NIN))
        rng = np.random.RandomState(7)
        w = sd.var("w", rng.randn(NIN, NOUT).astype(np.float32))
        b = sd.var("b", np.zeros(NOUT, np.float32))
        sd.nn.softmax(x.mmul(w).add(b), name="probs")
        return sd

    def test_samediff_exec_adapter_serves(self):
        from deeplearning4j_tpu.serving import samediff_forward
        sd = self._samediff()
        sv = ModelServer(samediff_forward(sd, ["probs"]), batch_limit=8,
                         coalesce_ms=0.5)
        try:
            sv.warmup([(NIN,)])
            x = feats(3)
            np.testing.assert_allclose(
                sv.output(x), np.asarray(sd.output({"x": x}, ["probs"])
                                         ["probs"]), rtol=1e-6)
            assert sv.recompiles_after_warmup() == 0
        finally:
            sv.close()

    def test_samediff_without_adapter_raises(self):
        with pytest.raises(TypeError, match="samediff_forward"):
            ModelServer(self._samediff(), batch_limit=8)

    def test_samediff_adapter_needs_unambiguous_input(self):
        from deeplearning4j_tpu.serving import samediff_forward
        sd = self._samediff()
        sd.placeHolder("extra", shape=(None, 2))
        with pytest.raises(ValueError, match="placeholders"):
            samediff_forward(sd, ["probs"])
        fwd = samediff_forward(sd, ["probs"], input_name="x")
        x = feats(2)
        assert np.asarray(fwd(x)).shape == (2, NOUT)

    def test_unservable_object_raises(self):
        with pytest.raises(TypeError, match="cannot serve"):
            ModelServer(object(), batch_limit=8)


# ==================================================== results-only D2H (I12)
class TestResultsOnlyD2H:
    """ISSUE 12 tentpole (c): on-device post-processing heads so D2H
    moves results, not logits — billed by
    ``dl4j_serving_d2h_bytes_total``."""

    @staticmethod
    def _d2h():
        from deeplearning4j_tpu import profiler as prof
        return prof.get_registry().get("dl4j_serving_d2h_bytes_total").value

    def _delta_for_one_dispatch(self, sv, rows=4, seed=9):
        before = self._d2h()
        out = sv.output(feats(rows, seed=seed))
        return out, self._d2h() - before

    def test_argmax_head_matches_and_shrinks_d2h(self, net):
        full = make_server(net, coalesce_ms=0.5)
        full.warmup([(NIN,)])
        _, full_bytes = self._delta_for_one_dispatch(full)
        full.close()

        sv = make_server(net, coalesce_ms=0.5, head="argmax")
        try:
            sv.warmup([(NIN,)])
            labels, head_bytes = self._delta_for_one_dispatch(sv)
            np.testing.assert_array_equal(
                labels, np.argmax(np.asarray(net.output(feats(4, seed=9))),
                                  axis=-1))
            # THE acceptance assert: the per-batch copy measurably
            # shrank (argmax of the padded bucket vs bucket x NOUT
            # floats)
            assert 0 < head_bytes < full_bytes, (head_bytes, full_bytes)
        finally:
            sv.close()

    def test_top_k_head_values_and_indices(self, net):
        sv = make_server(net, coalesce_ms=0.5, head="top_k:2")
        try:
            sv.warmup([(NIN,)])
            x = feats(3, seed=3)
            vals, idx = sv.output(x)
            logits = np.asarray(net.output(x))
            want_idx = np.argsort(-logits, axis=-1)[:, :2]
            np.testing.assert_array_equal(idx, want_idx)
            np.testing.assert_allclose(
                vals, np.take_along_axis(logits, want_idx, axis=-1),
                rtol=1e-6)
        finally:
            sv.close()

    def test_softmax_head(self, net):
        sv = make_server(net, coalesce_ms=0.5, head="softmax")
        try:
            sv.warmup([(NIN,)])
            probs = sv.output(feats(2))
            np.testing.assert_allclose(probs.sum(axis=-1), [1.0, 1.0],
                                       rtol=1e-5)
        finally:
            sv.close()

    def test_callable_head(self, net):
        import jax.numpy as jnp
        sv = make_server(net, coalesce_ms=0.5,
                         head=lambda y: jnp.max(y, axis=-1))
        try:
            sv.warmup([(NIN,)])
            x = feats(2)
            np.testing.assert_allclose(
                sv.output(x), np.asarray(net.output(x)).max(axis=-1),
                rtol=1e-6)
        finally:
            sv.close()

    def test_unknown_head_rejected(self, net):
        with pytest.raises(ValueError, match="unknown head"):
            make_server(net, head="argmin")

    def test_zero_recompiles_with_head(self, net):
        sv = make_server(net, coalesce_ms=0.5, head="argmax")
        try:
            sv.warmup([(NIN,)])
            for rows in (1, 3, 8, 5, 2):
                sv.output(feats(rows, seed=rows))
            assert sv.recompiles_after_warmup() == 0
        finally:
            sv.close()


# ===================================================== autoscaling hints (I12)
class TestLoadHints:
    def test_hints_shape_and_shed_accounting(self, net):
        sv = ModelServer(_SlowModel(net, 0.1), batch_limit=1, max_queue=2,
                         coalesce_ms=0.0, name="hints-test")
        try:
            sv.warmup([(NIN,)])
            reqs = [sv.submit(feats(1, seed=i)) for i in range(2)]
            shed = 0
            for i in range(4):      # queue bound 2 -> overload sheds
                try:
                    reqs.append(sv.submit(feats(1, seed=10 + i)))
                except ServerOverloadedError:
                    shed += 1
            assert shed >= 1
            for r in reqs:
                r.get(30.0)
            hints = sv.load_hints()
            assert hints["server"] == "hints-test"
            assert hints["queue_depth"] == 0
            assert hints["max_queue"] == 2
            assert hints["shed"] == shed
            assert 0 < hints["shed_rate"] < 1
            assert hints["breaker"] == "closed"
            assert hints["buckets"] == sv.buckets()
            assert hints["batch_occupancy_mean"] is not None
            assert hints["recompiles_after_warmup"] == 0
            from deeplearning4j_tpu import profiler as prof
            g = prof.get_registry().get("dl4j_serving_shed_ratio")
            assert g.labels(server="hints-test").value == \
                pytest.approx(hints["shed_rate"])
        finally:
            sv.close()


class TestImportedModelWarmupGate:
    """ISSUE 18: warmup(strict=True) on a SameDiff-backed server runs
    the FULL graph lints (including any import_report findings) — a bad
    import cannot reach ready=True."""

    def _sd(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, NIN))
        w = sd.var("w", np.random.RandomState(7)
                   .randn(NIN, NOUT).astype(np.float32))
        sd.nn.softmax(x.mmul(w), name="probs")
        return sd

    def test_strict_warmup_raises_on_import_error(self):
        from deeplearning4j_tpu.analysis import (Diagnostic, ModelValidationError,
                                                 Severity, ValidationReport)
        from deeplearning4j_tpu.serving import samediff_forward
        sd = self._sd()
        sd.import_report = ValidationReport(
            [Diagnostic("DL4J-E163", Severity.ERROR, "initializer 'w'",
                        "seeded import-time narrowing error")],
            subject="import")
        sv = ModelServer(samediff_forward(sd, ["probs"]), batch_limit=8)
        try:
            with pytest.raises(ModelValidationError, match="DL4J-E163"):
                sv.warmup([(NIN,)], strict=True)
            assert not sv.ready
        finally:
            sv.close()

    def test_strict_warmup_passes_clean_import(self):
        from deeplearning4j_tpu.serving import samediff_forward
        sd = self._sd()
        sv = ModelServer(samediff_forward(sd, ["probs"]), batch_limit=8)
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")   # W112 cache advice
                sv.warmup([(NIN,)], strict=True)
            assert sv.ready
        finally:
            sv.close()
