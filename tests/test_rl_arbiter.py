"""RL (DQN/CartPole) + Arbiter (hyperparameter search) tests
(ref: rl4j's QLearningDiscreteDense cartpole smoke + arbiter's
LocalOptimizationRunner tests — SURVEY.md §2.2 "Aux RL4J + Arbiter")."""

import numpy as np
import pytest

from deeplearning4j_tpu.arbiter import (ContinuousSpace, DiscreteSpace,
                                        GridSearchCandidateGenerator,
                                        IntegerSpace,
                                        OptimizationConfiguration,
                                        OptimizationRunner,
                                        RandomSearchGenerator)
from deeplearning4j_tpu.rl import (CartPole, ExpReplay,
                                   QLearningConfiguration,
                                   QLearningDiscreteDense)


class TestCartPole:
    def test_dynamics_and_termination(self):
        env = CartPole(seed=0)
        obs = env.reset()
        assert obs.shape == (4,)
        assert env.getActionSpace().n == 2
        total, steps = 0.0, 0
        done = False
        while not done:
            obs, r, done = env.step(1)   # constant push falls over quickly
            total += r
            steps += 1
        assert steps < CartPole.MAX_STEPS   # constant action must fail early
        assert total == steps               # +1 per step

    def test_episode_caps_at_max_steps(self):
        env = CartPole(seed=1)
        env.reset()
        # alternating push keeps it up a while but MAX_STEPS caps any run
        done, steps = False, 0
        while not done and steps < 500:
            _, _, done = env.step(steps % 2)
            steps += 1
        assert steps <= CartPole.MAX_STEPS


class TestExpReplay:
    def test_ring_buffer_and_sampling(self):
        rep = ExpReplay(capacity=8, obs_dim=3, seed=0)
        for i in range(12):          # wraps past capacity
            rep.store(np.full(3, i, np.float32), i % 2, float(i),
                      np.full(3, i + 1, np.float32), i % 3 == 0)
        assert len(rep) == 8
        s, a, r, s2, d = rep.getBatch(16)
        assert s.shape == (16, 3) and a.shape == (16,)
        assert r.min() >= 4.0        # oldest entries overwritten


class TestDQN:
    def test_learns_cartpole(self):
        mdp = CartPole(seed=0)
        conf = QLearningConfiguration(
            seed=1, max_step=6000, epsilon_nb_step=2500, update_start=300,
            target_dqn_update_freq=250, learning_rate=1e-3, batch_size=64)
        dqn = QLearningDiscreteDense(mdp, conf, hidden=(48, 48)).train()
        avg = dqn.evaluate(10)
        # random policy averages ~20 steps; learned policy must do far better
        assert avg > 80.0, avg

    def test_policy_is_greedy_and_deterministic(self):
        mdp = CartPole(seed=3)
        conf = QLearningConfiguration(seed=2, max_step=400, update_start=100,
                                      batch_size=32)
        dqn = QLearningDiscreteDense(mdp, conf, hidden=(16,)).train()
        policy = dqn.getPolicy()
        obs = mdp.reset()
        assert policy(obs) == policy(obs)
        assert policy(obs) in (0, 1)


class TestArbiter:
    def test_grid_search_covers_product(self):
        gen = GridSearchCandidateGenerator(
            {"lr": ContinuousSpace(0.1, 0.3), "units": DiscreteSpace([8, 16])},
            discretization_count=3)
        cands = list(gen)
        assert len(cands) == 6
        assert {c["units"] for c in cands} == {8, 16}

    def test_random_search_respects_spaces(self):
        gen = RandomSearchGenerator(
            {"lr": ContinuousSpace(1e-4, 1e-1, log=True),
             "n": IntegerSpace(2, 5)}, seed=0)
        it = iter(gen)
        for _ in range(20):
            c = next(it)
            assert 1e-4 <= c["lr"] <= 1e-1
            assert 2 <= c["n"] <= 5

    def test_runner_finds_known_optimum(self):
        # score surface with a known minimum at lr=0.2, units=16
        def score(cand):
            return (cand["lr"] - 0.2) ** 2 + (0.1 if cand["units"] != 16 else 0)

        runner = OptimizationRunner(OptimizationConfiguration(
            candidate_generator=GridSearchCandidateGenerator(
                {"lr": ContinuousSpace(0.0, 0.4),
                 "units": DiscreteSpace([8, 16])}, discretization_count=5),
            score_function=score, max_candidates=10, minimize=True))
        best = runner.execute()
        assert best.candidate["lr"] == pytest.approx(0.2)
        assert best.candidate["units"] == 16
        assert runner.numCandidatesCompleted() == 10

    def test_runner_trains_real_networks(self):
        """End-to-end: search learning rates for a real MultiLayerNetwork
        on a toy problem (the reference's MLPHyperparameterOptimization
        example shape)."""
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.nn.config import (InputType,
                                                  NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.train import updaters

        rng = np.random.RandomState(0)
        x = rng.randn(64, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
        ds = DataSet(x, y)

        def score(cand):
            conf = (NeuralNetConfiguration.Builder().seed(7)
                    .updater(updaters.Adam(cand["lr"])).list()
                    .layer(DenseLayer(nOut=8, activation="relu"))
                    .layer(OutputLayer(nOut=2, lossFunction="mcxent",
                                       activation="softmax"))
                    .setInputType(InputType.feedForward(4)).build())
            net = MultiLayerNetwork(conf).init()
            for _ in range(15):
                net.fit(ds)
            return float(net.score()), net

        runner = OptimizationRunner(OptimizationConfiguration(
            candidate_generator=DiscreteSearch({"lr": [1e-5, 3e-2]}),
            score_function=score, max_candidates=2, minimize=True,
            keep_models=True))
        best = runner.execute()
        assert best.candidate["lr"] == pytest.approx(3e-2)  # 1e-5 barely moves
        assert best.model is not None


def DiscreteSearch(space_values):
    """Tiny helper: exhaustive generator over explicit value lists."""
    from deeplearning4j_tpu.arbiter import (DiscreteSpace,
                                            GridSearchCandidateGenerator)
    return GridSearchCandidateGenerator(
        {k: DiscreteSpace(v) for k, v in space_values.items()},
        discretization_count=max(len(v) for v in space_values.values()))


class TestA3C:
    """A3C + policy abstraction (VERDICT r3 #10; ref: rl4j
    A3CDiscreteDense, Policy/ACPolicy/DQNPolicy/EpsGreedy)."""

    def test_policies(self):
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.rl.a3c import ACPolicy, DQNPolicy, EpsGreedy

        def fake_net(params, x):
            return jnp.asarray([[0.0, 10.0]])
        pol = DQNPolicy(fake_net, {})
        assert pol.nextAction(np.zeros(4)) == 1
        ac = ACPolicy(fake_net, {}, deterministic=True)
        assert ac.nextAction(np.zeros(4)) == 1
        eg = EpsGreedy(pol, action_space_n=2, eps_start=1.0, eps_end=1.0,
                       anneal_steps=1, seed=0)
        acts = {eg.nextAction(np.zeros(4)) for _ in range(50)}
        assert acts == {0, 1}          # fully exploring

    def test_a3c_solves_cartpole(self):
        from deeplearning4j_tpu.rl.a3c import (A3CConfiguration,
                                               A3CDiscreteDense)
        from deeplearning4j_tpu.rl.mdp import CartPole
        conf = A3CConfiguration(seed=7, num_threads=2, max_steps=5000,
                                learning_rate=7e-3, n_step=32,
                                max_episode_steps=200)
        a3c = A3CDiscreteDense(CartPole, conf, hidden=(64,))
        # asynchronous worker/trainer interleaving makes any single run
        # noisy; "solved" = SOME 10-episode window of the (stochastic)
        # training rewards sustains a mean > 150 (cap: 4 rounds = 60k
        # env steps; a random policy averages ~20, the cap is 200)
        def best_window(rs, w=10):
            if len(rs) < w:
                return 0.0
            return max(float(np.mean(rs[i:i + w]))
                       for i in range(len(rs) - w + 1))
        # on-policy PG oscillates; train in 5k-step chunks (cap 60k) and
        # accept the first chunk where the policy BOTH sustained a
        # 150+/200 training window AND plays >80 on fresh episodes with
        # the params of that moment (the stochastic policy A3C optimizes)
        mdp = CartPole(seed=3)
        solved = False
        for _ in range(12):
            a3c.train()
            if best_window(a3c.episode_rewards) <= 150.0:
                continue
            pol = a3c.getPolicy(deterministic=False)
            plays = [pol.play(mdp, max_steps=200) for _ in range(5)]
            if np.mean(plays) > 80.0:
                solved = True
                break
        assert solved, a3c.episode_rewards[-12:]
