"""GSPMD-native sharded training engine tests (ISSUE 15).

Tier 1 — ShardedTrainingPlan / GSPMDTrainer: one jit-with-shardings
fit, bit-exact against the ParallelWrapper replication path and the
megastep, float-ulp-close to the single-device fit (the wrapper's
long-standing envelope), zero steady-state recompiles.
Tier 2 — ZeRO updater-state sharding: per-device optimizer HBM
measured at ~1/n_data, bit-exact math, checkpoint save -> reshard ->
resume.
Tier 3 lives in tests/test_multihost.py (socket/file coordinators,
``pytest -m multihost``).
"""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.distributed import (GSPMDTrainer,
                                            ShardedTrainingPlan, ZeroPlan,
                                            gather_opt_state,
                                            updater_hbm_bytes)
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import (DenseLayer, DropoutLayer,
                                          OutputLayer)
from deeplearning4j_tpu.parallel import DeviceMesh, ParallelWrapper
from deeplearning4j_tpu.parallel import checkpoint as ckpt
from deeplearning4j_tpu.train import updaters


@pytest.fixture(scope="module")
def devices8():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return jax.devices()


def _net(dropout: bool = False, seed: int = 7):
    b = (NeuralNetConfiguration.Builder().seed(seed)
         .updater(updaters.Adam(0.01)).list()
         .layer(DenseLayer(nOut=32, activation="relu")))
    if dropout:
        b = b.layer(DropoutLayer(0.25))
    conf = (b.layer(DenseLayer(nOut=16, activation="relu"))
            .layer(OutputLayer(nOut=4, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(16))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 16).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, n)]
    return DataSet(X, Y)


# ===================================================== ShardedTrainingPlan
class TestShardedTrainingPlan:
    def test_batch_spec_shards_dim0_and_mega_dim1(self, devices8):
        plan = ShardedTrainingPlan(DeviceMesh.data_parallel())
        assert plan.batch_spec(2) == P("data", None)
        assert plan.batch_spec(2, mega=True) == P(None, "data")
        assert plan.batch_spec(1) == P("data")
        assert plan.batch_spec(1, mega=True) == P(None)

    def test_model_axis_mesh_replicates_batch_over_model(self, devices8):
        """The PR-2 carried follow-up: placement derives from the plan's
        batch PartitionSpec — on a data=2 x model=4 mesh the batch
        shards 2 ways and REPLICATES over the model axis (every device
        holds a slice: 8 devices in the sharding's device set)."""
        mesh = DeviceMesh.create(data=2, model=4)
        plan = ShardedTrainingPlan(mesh)
        x = plan.place(np.ones((8, 16), np.float32))
        assert len(x.sharding.device_set) == 8
        assert x.sharding.spec == P("data", None)
        mx = plan.place(np.ones((3, 8, 16), np.float32), mega=True)
        assert mx.sharding.spec == P(None, "data", None)

    def test_param_rules_and_names(self, devices8):
        net = _net()
        mesh = DeviceMesh.create(data=2, model=4)
        plan = ShardedTrainingPlan(mesh, rules={r"/W$": (None, "model")})
        sh = plan.param_shardings(net)
        assert sh[0]["W"].spec == P(None, "model")
        assert sh[0]["b"].spec == P()

    def test_zero_state_spec_composes_with_param_spec(self):
        z = ZeroPlan(min_bytes=0)
        # free dim 0 divisible: data goes there
        assert z.state_spec((None, "model"), (16, 32), 4, 8) == \
            P("data", "model")
        # dim 0 taken: next free divisible dim
        assert z.state_spec(("model", None), (16, 32), 4, 8) == \
            P("model", "data")
        # nothing divisible: param spec unchanged
        assert z.state_spec((None,), (3,), 4, 8) == P(None)
        # below min_bytes: untouched
        big = ZeroPlan(min_bytes=10 ** 9)
        assert big.state_spec((None, None), (16, 32), 4, 8) == P(None, None)
        # FSDP-style param already sharded over the ZeRO axis: the state
        # inherits it — no duplicate-axis spec (NamedSharding rejects
        # those), no double division
        assert z.state_spec(("data", None), (16, 32), 4, 8) == \
            P("data", None)

    def test_fsdp_style_data_axis_params_train(self, devices8):
        """Param sharding over the DATA axis (FSDP-style) + ZeRO: the
        state inherits the param partitioning and the fit runs."""
        net = _net()
        plan = ShardedTrainingPlan(
            DeviceMesh.data_parallel(),
            rules={r"/W$": ("data", None)}, zero=ZeroPlan(min_bytes=0))
        GSPMDTrainer(net, plan).fit(
            ListDataSetIterator(_data(16), 16), epochs=1)
        assert net._opt_state[0]["W"]["m"].sharding.spec == P("data", None)
        assert np.isfinite(float(net.score()))

    def test_signature_busts_step_caches(self, devices8):
        net = _net()
        plan = ShardedTrainingPlan(DeviceMesh.data_parallel())
        net.setShardingPlan(plan)
        plan.apply(net)
        net._fit_one(_data(16))
        assert net._train_step_cache
        # equal plan: caches kept
        net.setShardingPlan(ShardedTrainingPlan(DeviceMesh.data_parallel()))
        assert net._train_step_cache
        # different plan (ZeRO added): busted
        net.setShardingPlan(ShardedTrainingPlan(DeviceMesh.data_parallel(),
                                                zero=True))
        assert not net._train_step_cache

    def test_bad_batch_axis_rejected(self, devices8):
        with pytest.raises(ValueError, match="batch axis"):
            ShardedTrainingPlan(DeviceMesh.data_parallel(),
                                batch_axes=("nope",))


# ============================================================ GSPMD parity
class TestGSPMDParity:
    def test_bit_exact_vs_wrapper_ulp_close_to_single(self, devices8):
        """The acceptance pin: ONE jit-with-shardings fit on the data=8
        mesh is bit-exact vs ParallelWrapper replication (identical
        compiled program) and float-ulp-close to the single-device fit
        (reduction grouping differs across device counts — the same
        envelope the wrapper has always had). Dropout included: the
        fold_in(seed, t) RNG must partition bit-stably."""
        it = lambda: ListDataSetIterator(_data(), 16)
        single = _net(dropout=True)
        single.fit(it(), epochs=2)

        wrapped = _net(dropout=True)
        ParallelWrapper(wrapped, DeviceMesh.data_parallel()).fit(
            it(), epochs=2)

        gspmd = _net(dropout=True)
        GSPMDTrainer(gspmd, ShardedTrainingPlan(
            DeviceMesh.data_parallel())).fit(it(), epochs=2)

        p_single = np.asarray(single.params())
        p_wrap = np.asarray(wrapped.params())
        p_gspmd = np.asarray(gspmd.params())
        np.testing.assert_array_equal(p_gspmd, p_wrap)       # bit-exact
        np.testing.assert_allclose(p_gspmd, p_single, rtol=0, atol=2e-6)
        # losses too
        assert float(gspmd.score()) == float(wrapped.score())

    def test_megastep_bit_exact_and_zero_recompiles(self, devices8):
        """fit(steps_per_dispatch=3) through the plan == K=1, bit-exact,
        with dropout; and the K=1 path's compiled step holds ONE jit
        trace after 12 steps (zero steady-state recompiles — the churn
        detector sees one signature)."""
        from deeplearning4j_tpu.analysis import churn as _churn
        it = lambda: ListDataSetIterator(_data(96), 16)
        a = _net(dropout=True)
        GSPMDTrainer(a, ShardedTrainingPlan(DeviceMesh.data_parallel())).fit(
            it(), epochs=2)
        b = _net(dropout=True)
        GSPMDTrainer(b, ShardedTrainingPlan(DeviceMesh.data_parallel())).fit(
            it(), epochs=2, steps_per_dispatch=3)
        np.testing.assert_array_equal(np.asarray(a.params()),
                                      np.asarray(b.params()))
        step = a._train_step_cache[(False, False)]
        assert step._jit._cache_size() == 1
        assert _churn.get_churn_detector().signature_count(
            "MultiLayerNetwork.fit", owner=a) == 1

    def test_model_axis_mesh_one_code_path(self, devices8):
        """data=2 x model=4 with a W-sharding rule: same fit() call, one
        compiled program, result ulp-close to single-device — tensor
        parallelism is a declaration, not a separate path. K=2 rides
        the DevicePrefetcher with plan-derived placement."""
        it = lambda: ListDataSetIterator(_data(), 16)
        single = _net()
        single.fit(it(), epochs=2)
        mesh = DeviceMesh.create(data=2, model=4)
        tp = _net()
        GSPMDTrainer(tp, ShardedTrainingPlan(
            mesh, rules={r"/W$": (None, "model")})).fit(
            it(), epochs=2, steps_per_dispatch=2)
        np.testing.assert_allclose(np.asarray(tp.params()),
                                   np.asarray(single.params()),
                                   rtol=0, atol=2e-6)
        assert tp._params[0]["W"].sharding.spec == P(None, "model")

    def test_computation_graph_same_hooks(self, devices8):
        """ComputationGraph gets the identical plan treatment: node-name
        rules, ZeRO composition, megasteps — ulp-close to the plain
        graph fit."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        def graph():
            g = (NeuralNetConfiguration.Builder().seed(4)
                 .updater(updaters.Adam(0.01))
                 .graphBuilder()
                 .addInputs("in")
                 .setInputTypes(InputType.feedForward(16)))
            g.addLayer("fc", DenseLayer(nOut=32, activation="relu"), "in")
            g.addLayer("out", OutputLayer(nOut=4, lossFunction="mcxent",
                                          activation="softmax"), "fc")
            g.setOutputs("out")
            return ComputationGraph(g.build()).init()

        ds = _data()
        a = graph()
        a.fit(ListDataSetIterator(ds, 16), epochs=2)
        b = graph()
        plan = ShardedTrainingPlan(DeviceMesh.create(data=2, model=4),
                                   rules={r"fc/W$": (None, "model")},
                                   zero=ZeroPlan(min_bytes=0))
        GSPMDTrainer(b, plan).fit(ListDataSetIterator(ds, 16), epochs=2,
                                  steps_per_dispatch=2)
        np.testing.assert_allclose(np.asarray(b.params()),
                                   np.asarray(a.params()),
                                   rtol=0, atol=2e-6)
        assert b._params["fc"]["W"].sharding.spec == P(None, "model")
        assert b._opt_state["fc"]["W"]["m"].sharding.spec == \
            P("data", "model")

    def test_uneven_batch_pads_with_zero_weight(self, devices8):
        net = _net()
        tr = GSPMDTrainer(net, ShardedTrainingPlan(
            DeviceMesh.data_parallel()))
        tr.fit(ListDataSetIterator(_data(13), 13), epochs=1)  # 13 % 8 != 0
        assert np.isfinite(float(net.score()))

    def test_pad_to_data_axis_handles_multidataset(self):
        """Multi-input/-output graph batches pad too: every array grows
        to the shard multiple and every output gets a zero-weight tail
        mask."""
        from deeplearning4j_tpu.data.dataset import MultiDataSet
        from deeplearning4j_tpu.parallel.data import pad_to_data_axis
        rng = np.random.RandomState(0)
        mds = MultiDataSet(
            [rng.randn(13, 4).astype(np.float32),
             rng.randn(13, 6).astype(np.float32)],
            [np.eye(3, dtype=np.float32)[rng.randint(0, 3, 13)]])
        out = pad_to_data_axis(mds, 8)
        assert out.features[0].shape == (16, 4)
        assert out.features[1].shape == (16, 6)
        assert out.labels[0].shape == (16, 3)
        np.testing.assert_array_equal(out.labels_masks[0][:13], 1.0)
        np.testing.assert_array_equal(out.labels_masks[0][13:], 0.0)

    def test_warmup_precompiles_the_dispatched_program(self, devices8):
        net = _net()
        tr = GSPMDTrainer(net, ShardedTrainingPlan(
            DeviceMesh.data_parallel()))
        tr.warmup([((16, 16), (16, 4))])
        step = net._train_step_cache[(False, False)]
        assert step.warmed_signatures() == 1
        tr.fit(ListDataSetIterator(_data(16), 16), epochs=1)
        assert np.isfinite(float(net.score()))

    def test_resilience_checkpoint_resume_replaces_onto_plan(self,
                                                             devices8,
                                                             tmp_path):
        """checkpoint= composes: a fresh model resuming the newest
        checkpoint restores HOST arrays — the per-dispatch
        ensure_placed guard re-places them per the plan, and the
        restored state is bit-exact with the donor's."""
        from deeplearning4j_tpu.train.resilience import CheckpointConfig
        d = str(tmp_path / "ck")
        it = lambda: ListDataSetIterator(_data(64), 16)
        a = _net()
        GSPMDTrainer(a, ShardedTrainingPlan(
            DeviceMesh.data_parallel(), zero=ZeroPlan(min_bytes=0))).fit(
            it(), epochs=2, checkpoint=CheckpointConfig(d, every_steps=4))
        b = _net(seed=99)
        GSPMDTrainer(b, ShardedTrainingPlan(
            DeviceMesh.data_parallel(), zero=ZeroPlan(min_bytes=0))).fit(
            it(), epochs=2, checkpoint=CheckpointConfig(d, resume=True))
        np.testing.assert_array_equal(np.asarray(a.params()),
                                      np.asarray(b.params()))

    def test_validate_carries_plan_declaration(self, devices8):
        net = _net()
        tr = GSPMDTrainer(net, ShardedTrainingPlan(
            DeviceMesh.data_parallel(), zero=ZeroPlan()))
        report = tr.validate(batch_size=16)
        assert "DL4J-E102" not in report.codes()


# ================================================================== ZeRO
class TestZeroShardedUpdaterState:
    def test_opt_state_sharded_and_hbm_measured(self, devices8):
        """The tier-2 acceptance pin: measured per-device updater-state
        bytes on the data=8 mesh at ~1/8 of the replicated path (small
        non-divisible tensors stay replicated, so the bound is <=0.2x,
        not exactly 0.125x)."""
        rep = _net()
        GSPMDTrainer(rep, ShardedTrainingPlan(
            DeviceMesh.data_parallel())).fit(
            ListDataSetIterator(_data(), 16), epochs=1)
        zero = _net()
        GSPMDTrainer(zero, ShardedTrainingPlan(
            DeviceMesh.data_parallel(), zero=ZeroPlan(min_bytes=0))).fit(
            ListDataSetIterator(_data(), 16), epochs=1)
        # moments sharded over data
        assert zero._opt_state[0]["W"]["m"].sharding.spec == P("data")
        hb_rep = updater_hbm_bytes(rep._opt_state, record=False)
        hb_zero = updater_hbm_bytes(zero._opt_state, record=True)
        assert len(hb_rep) == 8 and len(hb_zero) == 8
        ratio = sum(hb_zero.values()) / sum(hb_rep.values())
        assert ratio <= 0.2, ratio
        # the gauge is published per device
        from deeplearning4j_tpu import profiler as _prof
        text = _prof.get_registry().exposition()
        assert "dl4j_updater_hbm_bytes" in text

    def test_zero_math_bit_exact(self, devices8):
        """Cross-replica weight-update sharding is element-wise: the
        sharded-state fit is BIT-exact with the replicated-state fit."""
        it = lambda: ListDataSetIterator(_data(), 16)
        a = _net()
        GSPMDTrainer(a, ShardedTrainingPlan(
            DeviceMesh.data_parallel())).fit(it(), epochs=2)
        b = _net()
        GSPMDTrainer(b, ShardedTrainingPlan(
            DeviceMesh.data_parallel(), zero=ZeroPlan(min_bytes=0))).fit(
            it(), epochs=2)
        np.testing.assert_array_equal(np.asarray(a.params()),
                                      np.asarray(b.params()))

    def test_gather_opt_state_seam(self, devices8):
        net = _net()
        GSPMDTrainer(net, ShardedTrainingPlan(
            DeviceMesh.data_parallel(), zero=ZeroPlan(min_bytes=0))).fit(
            ListDataSetIterator(_data(16), 16), epochs=1)
        host = gather_opt_state(net._opt_state)
        for leaf in jax.tree_util.tree_leaves(host):
            assert isinstance(leaf, np.ndarray)
        m = np.asarray(jax.device_get(net._opt_state[0]["W"]["m"]))
        np.testing.assert_array_equal(host[0]["W"]["m"], m)


class TestZeroCheckpointReshard:
    def _fit_steps(self, trainer, n_batches):
        ds = _data(16 * n_batches, seed=3)
        trainer.fit(ListDataSetIterator(ds, 16), epochs=1)

    def test_same_mesh_resume_bit_exact(self, devices8, tmp_path):
        """save_sharded at step k -> restore -> continue == the
        uninterrupted run, bit-exact (same data=8 mesh + ZeRO plan)."""
        plan = lambda: ShardedTrainingPlan(DeviceMesh.data_parallel(),
                                           zero=ZeroPlan(min_bytes=0))
        a = _net()
        ta = GSPMDTrainer(a, plan())
        self._fit_steps(ta, 4)
        d = str(tmp_path / "zck")
        ckpt.save_sharded(d, {"params": a._params, "opt": a._opt_state},
                          step=a._iteration)
        self._fit_steps(ta, 4)          # uninterrupted reference
        ref = np.asarray(a.params())

        b = _net(seed=99)               # different init: restore must win
        tb = GSPMDTrainer(b, plan())
        tb.plan.apply(b)
        restored, step = ckpt.load_sharded(d, {"params": b._params,
                                               "opt": b._opt_state})
        b._params, b._opt_state = restored["params"], restored["opt"]
        b._iteration, b._t_dev = step, None
        self._fit_steps(tb, 4)
        np.testing.assert_array_equal(ref, np.asarray(b.params()))

    def test_reshard_to_smaller_mesh_restores_bit_exact(self, devices8,
                                                        tmp_path):
        """A checkpoint written under data=8 ZeRO sharding loads into a
        data=4 plan: every restored leaf is bit-exact (load_sharded
        stitches the narrower shards) and training continues — the
        elastic shrink/grow resume path for sharded optimizer state."""
        a = _net()
        ta = GSPMDTrainer(a, ShardedTrainingPlan(
            DeviceMesh.data_parallel(), zero=ZeroPlan(min_bytes=0)))
        self._fit_steps(ta, 4)
        d = str(tmp_path / "zck2")
        ckpt.save_sharded(d, {"params": a._params, "opt": a._opt_state},
                          step=a._iteration)
        saved_m = np.asarray(jax.device_get(a._opt_state[0]["W"]["m"]))

        mesh4 = DeviceMesh.create(data=4, model=1, seq=1,
                                  devices=jax.devices()[:4])
        b = _net(seed=99)
        tb = GSPMDTrainer(b, ShardedTrainingPlan(
            mesh4, zero=ZeroPlan(min_bytes=0)))
        tb.plan.apply(b)
        restored, step = ckpt.load_sharded(d, {"params": b._params,
                                               "opt": b._opt_state})
        b._params, b._opt_state = restored["params"], restored["opt"]
        b._iteration, b._t_dev = step, None
        # restored values bit-exact under the NEW narrower sharding
        got_m = b._opt_state[0]["W"]["m"]
        assert len(got_m.sharding.device_set) == 4
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(got_m)), saved_m)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(b._params[0]["W"])),
            np.asarray(jax.device_get(a._params[0]["W"])))
        # and the resumed fit runs on the survivor mesh
        self._fit_steps(tb, 2)
        assert np.isfinite(float(b.score()))


# ==================================================== analysis satellites
class TestDistributionAnalysis:
    def _big(self):
        return (NeuralNetConfiguration.Builder().seed(1)
                .updater(updaters.Adam(1e-3)).list()
                .layer(DenseLayer(nOut=4096, activation="relu"))
                .layer(OutputLayer(nOut=8))
                .setInputType(InputType.feedForward(4096))
                .build())

    def test_w109_replicated_optimizer_state(self):
        report = self._big().validate(mesh="data=8")
        w109 = [d for d in report if d.code == "DL4J-W109"]
        assert w109 and "optimizer" in w109[0].message
        # declared ZeRO: quiet
        assert "DL4J-W109" not in self._big().validate(
            mesh="data=8", zero=True).codes()
        # single data device: replication is free
        assert "DL4J-W109" not in self._big().validate(
            mesh="data=1,model=8").codes()

    def test_w109_quiet_for_stateless_updater(self):
        conf = (NeuralNetConfiguration.Builder().seed(1)
                .updater(updaters.Sgd(0.1)).list()
                .layer(DenseLayer(nOut=4096, activation="relu"))
                .layer(OutputLayer(nOut=8))
                .setInputType(InputType.feedForward(4096))
                .build())
        assert "DL4J-W109" not in conf.validate(mesh="data=8").codes()

    def test_e104_counts_zero_sharded_updater_state(self):
        # params ~64 MiB, Adam state 128 MiB replicated. Budget 0.09 GiB:
        # passes with ZeRO over 8 shards (64 + 16 MiB), fails with the
        # state replicated-equivalent declared at data=1 (64 + 128 MiB)
        ok = self._big().validate(mesh="data=8", hbm_gb=0.09, zero=True)
        assert "DL4J-E104" not in ok.codes(), ok.format()
        tight = self._big().validate(mesh="data=1", hbm_gb=0.09, zero=True)
        e = [d for d in tight if d.code == "DL4J-E104"]
        assert e and "ZeRO" in e[0].message
        # without a zero declaration E104 keeps its params-only baseline
        base = self._big().validate(mesh="data=8", hbm_gb=0.09)
        assert "DL4J-E104" not in base.codes()

    def test_collective_estimate_matches_compiled_hlo(self, devices8):
        """The probe_collectives assertion, tier-1-sized: the W107 ring
        model is within 2x of the compiled GSPMD step's all-reduce
        bytes on the data=8 mesh."""
        from deeplearning4j_tpu.analysis.distribution import (
            estimate_gradient_collectives)
        from deeplearning4j_tpu.distributed.gspmd import (
            compiled_train_step_hlo, hlo_collective_bytes)
        net = _net()
        mesh = DeviceMesh.data_parallel()
        plan = ShardedTrainingPlan(mesh)
        net.setShardingPlan(plan)
        plan.apply(net)
        ds = _data(64)
        hlo = compiled_train_step_hlo(net, ds.features, ds.labels)
        coll = hlo_collective_bytes(hlo)
        ring = 2.0 * 7 / 8
        measured = ring * sum(coll.get(k, 0) for k in
                              ("all-reduce", "reduce-scatter",
                               "all-gather"))
        estimate = sum(estimate_gradient_collectives(
            net.conf, mesh.spec()).values())
        assert measured > 0
        assert 0.5 <= estimate / measured <= 2.0


# =========================================================== serving plan
class TestServingOnShardedMesh:
    def test_registry_stages_version_on_plan_mesh(self, devices8):
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        net = _net()
        ref = np.asarray(net.output(_data(8).features))
        mesh = DeviceMesh.create(data=2, model=4)
        plan = ShardedTrainingPlan(mesh, rules={r"/W$": (None, "model")})
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("m", net, shapes=[(16,)], plan=plan)
            assert net._params[0]["W"].sharding.spec == P(None, "model")
            out = reg.output("m", _data(8).features, timeout=30)
            np.testing.assert_allclose(np.asarray(out), ref,
                                       rtol=1e-4, atol=1e-5)
